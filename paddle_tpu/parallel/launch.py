"""Multi-host job launcher.

The TPU-native counterpart of the reference's cluster-launch tooling —
the ssh fan-out launcher (reference: paddle/scripts/cluster_train/
paddle.py: parse a node list, push env + start one trainer per node with
PADDLE_* variables) and the fabric/openmpi recipes under
scripts/cluster_train_v2/.

Two modes:

1. ssh fan-out (`launch_ssh`): start the SAME paddle_tpu command on every
   host with JAX coordinator env wired (process 0's host:port is the
   coordinator). Logs stream back with a host prefix; first failure
   tears the job down. This is the moral equivalent of the reference's
   `paddle.py --job_dispatch_package` flow without the rsync step (use a
   shared filesystem or image).

2. JobSet manifest (`emit_jobset`): print a Kubernetes JobSet YAML for a
   gang-scheduled multi-host TPU slice job — the contemporary way the
   reference's `cluster_train_v2` k8s recipes map to TPUs. jax's own
   auto-detection picks up coordinator/process-id inside the pods, so
   the container command needs no explicit flags.

3. Elastic local gang (`GangSupervisor`): spawn N trainer PROCESSES on
   this host, each joining a jax.distributed coordinator and running
   the ZeRO-sharded resilient loop (`run_gang_worker`). The supervisor
   watches exits and per-rank heartbeat files; a member that dies
   (SIGKILL, OOM, watchdog exit-75) or wedges (alive but no heartbeat)
   tears the whole barrier down and the gang REFORMS at the surviving
   count — the reshard-on-restore checkpoint path
   (`train.ElasticCheckpointManager`) makes the N-1 gang resume from
   the N-gang's last durable step. This is the local, testable
   analog of what `launch_ssh`/JobSet restart loops do across hosts.
"""

from __future__ import annotations

import dataclasses
import json
import os
import pathlib
import shlex
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from paddle_tpu.cluster.lease import LeaseTable


def _stream(proc: subprocess.Popen, prefix: str) -> None:
    for line in proc.stdout:  # type: ignore[union-attr]
        sys.stdout.write(f"[{prefix}] {line if isinstance(line, str) else line.decode()}")
        sys.stdout.flush()


def launch_ssh(hosts: Sequence[str], command: Sequence[str], *,
               coordinator_port: int = 1234,
               workdir: Optional[str] = None,
               python: str = "python",
               extra_env: Optional[Dict[str, str]] = None,
               ssh_opts: Sequence[str] = ("-o", "BatchMode=yes"),
               dry_run: bool = False) -> int:
    """Fan a paddle_tpu command out to N hosts over ssh.

    hosts: ssh destinations; hosts[0] is the coordinator.
    command: argv AFTER `python -m paddle_tpu`, e.g.
        ["train", "--config", "cfg.py", "--batch-size", "512"].
    Every process gets --coordinator/--num-processes/--process-id
    appended (wired to parallel.distributed.initialize by the CLI).

    Returns the first nonzero exit code (0 if all succeed). On any
    failure the remaining processes are terminated — the gang-scheduling
    semantic (a dead trainer must kill the barrier, unlike the
    reference's v1 where it simply hung; SURVEY §5).
    """
    coord = f"{hosts[0].split('@')[-1]}:{coordinator_port}"
    env = dict(extra_env or {})
    procs: List[subprocess.Popen] = []
    threads: List[threading.Thread] = []
    cmds: List[List[str]] = []
    for i, host in enumerate(hosts):
        argv = [python, "-m", "paddle_tpu", *command,
                "--coordinator", coord,
                "--num-processes", str(len(hosts)),
                "--process-id", str(i)]
        remote = ""
        if workdir:
            remote += f"cd {shlex.quote(workdir)} && "
        remote += " ".join(
            [f"{k}={shlex.quote(v)}" for k, v in env.items()]
            + [shlex.quote(a) for a in argv])
        cmds.append(["ssh", *ssh_opts, host, remote])

    if dry_run:
        for c in cmds:
            print(" ".join(shlex.quote(x) for x in c))
        return 0

    for host, c in zip(hosts, cmds):
        p = subprocess.Popen(c, stdout=subprocess.PIPE,
                             stderr=subprocess.STDOUT, text=True)
        t = threading.Thread(target=_stream, args=(p, host), daemon=True)
        t.start()
        procs.append(p)
        threads.append(t)

    rc = 0
    try:
        # wait for the first failure (or all successes)
        pending = set(range(len(procs)))
        while pending and rc == 0:
            for i in list(pending):
                code = procs[i].poll()
                if code is None:
                    continue
                pending.discard(i)
                if code != 0:
                    rc = code
            if pending and rc == 0:
                import time

                time.sleep(0.2)
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        for t in threads:
            t.join(timeout=5)
    return rc


def emit_jobset(name: str, *, image: str, command: Sequence[str],
                num_hosts: int, tpu_topology: str = "4x4",
                accelerator: str = "tpu-v5-lite-podslice",
                chips_per_host: int = 4,
                namespace: str = "default") -> str:
    """Render a JobSet YAML manifest for a gang-scheduled TPU job.

    command: argv after `python -m paddle_tpu` run in every pod; jax
    auto-detects coordinator/process ids from the TPU pod environment.
    """
    cmd_json = ", ".join(
        f'"{c}"' for c in ["python", "-m", "paddle_tpu", *command])
    return f"""apiVersion: jobset.x-k8s.io/v1alpha2
kind: JobSet
metadata:
  name: {name}
  namespace: {namespace}
spec:
  failurePolicy:
    maxRestarts: 3
  replicatedJobs:
  - name: workers
    template:
      spec:
        parallelism: {num_hosts}
        completions: {num_hosts}
        backoffLimit: 0
        template:
          spec:
            restartPolicy: Never
            nodeSelector:
              cloud.google.com/gke-tpu-accelerator: {accelerator}
              cloud.google.com/gke-tpu-topology: {tpu_topology}
            containers:
            - name: trainer
              image: {image}
              command: [{cmd_json}]
              resources:
                limits:
                  google.com/tpu: {chips_per_host}
"""


# ---------------------------------------------------------------------------
# elastic local gang: spec + worker + supervisor
# ---------------------------------------------------------------------------

#: repo root, for child PYTHONPATH/cwd (scripts.cpu_guard lives there)
_REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]


class GangFailedError(RuntimeError):
    """The gang cannot make progress: membership fell below
    `min_procs`, or the overall deadline expired. The last durable
    checkpoint is intact — a rerun with a fresh supervisor resumes."""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _atomic_json(path: pathlib.Path, payload: dict) -> None:
    """tmp + rename so a reader (the supervisor polling heartbeats, a
    worker killed mid-write) never sees a torn file."""
    tmp = path.with_name(path.name + ".tmp")
    tmp.write_text(json.dumps(payload))
    os.replace(tmp, path)


def _read_json(path: pathlib.Path) -> Optional[dict]:
    try:
        return json.loads(path.read_text())
    except (OSError, ValueError):
        return None


@dataclasses.dataclass
class GangSpec:
    """Everything a gang CHILD needs, JSON-serialized across the spawn
    boundary (the `serve.fleet.ReplicaSpec` idiom): the job itself is a
    `"module:function"` builder string the child imports and calls —
    no pickled closures cross the process boundary.

    The builder must return a dict with keys `model`, `loss_fn`,
    `optimizer`, `input_specs` (tuple of ShapeSpec for model.init) and
    `batches` (callable `total_steps -> iterable of (x, y)` GLOBAL
    numpy batches, deterministic — every rank derives its own slice,
    and a reformed gang replays the identical stream).
    """

    builder: str
    builder_kwargs: Dict[str, Any]
    checkpoint_dir: str
    workdir: str                  # heartbeats + per-rank result files
    total_steps: int
    checkpoint_every: int = 2
    seed: int = 0
    coordinator: Optional[str] = None
    num_processes: int = 1
    gang_epoch: int = 0
    watchdog_timeout_s: Optional[float] = None

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))

    @classmethod
    def from_json(cls, text: str) -> "GangSpec":
        return cls(**json.loads(text))


def gang_child_main() -> None:
    """Entry point for a spawned gang member (env-driven:
    PADDLE_TPU_GANG_SPEC = spec JSON path, PADDLE_TPU_GANG_RANK).
    `distributed.initialize` MUST be the first jax-touching call, so
    this runs before anything imports a model."""
    spec = GangSpec.from_json(
        pathlib.Path(os.environ["PADDLE_TPU_GANG_SPEC"]).read_text())
    rank = int(os.environ["PADDLE_TPU_GANG_RANK"])
    from paddle_tpu.parallel import distributed as D

    if spec.num_processes > 1:
        D.initialize(coordinator_address=spec.coordinator,
                     num_processes=spec.num_processes, process_id=rank)
    run_gang_worker(spec, rank)


def run_gang_worker(spec: GangSpec, rank: int) -> dict:
    """One gang member's whole life: build the job from the spec's
    builder string, land the state in the ZeRO layout for the GLOBAL
    mesh, and drive the resilient loop — restore (resharding if the
    checkpoint came from a different gang size), train, heartbeat
    after every step, checkpoint on cadence. Writes a per-rank result
    JSON (files, not stdout: a SIGKILLed sibling must not be able to
    truncate the survivor's report)."""
    import importlib

    import jax

    from paddle_tpu.core import mesh as mesh_lib
    from paddle_tpu.parallel.sharding import batch_sharding
    from paddle_tpu.parallel.train_step import make_zero_train_step
    from paddle_tpu.train import events as E
    from paddle_tpu.train.checkpoint import ElasticCheckpointManager
    from paddle_tpu.train.resilience import Preempted, ResilientTrainer
    from paddle_tpu.train.state import TrainState
    from paddle_tpu.train.trainer import Trainer

    devs = jax.devices()
    mesh = mesh_lib.build_mesh(
        mesh_lib.MeshConfig(data=len(devs)), devices=devs)

    mod_name, _, fn_name = spec.builder.partition(":")
    job = getattr(importlib.import_module(mod_name),
                  fn_name)(**spec.builder_kwargs)
    model, loss_fn = job["model"], job["loss_fn"]
    optimizer = job["optimizer"]

    trainer = Trainer(model, loss_fn, optimizer, seed=spec.seed)
    # Trainer.init_state, but landing in the ZeRO layout: same rng
    # split so every rank (and every gang size) inits identical params
    trainer._rng, init_rng = jax.random.split(trainer._rng)
    params, mstate = model.init(init_rng, *job["input_specs"])
    state = TrainState.create_zero(params, mstate, optimizer, mesh)

    manager = ElasticCheckpointManager(spec.checkpoint_dir, mesh=mesh)
    rt = ResilientTrainer(
        trainer, spec.checkpoint_dir,
        checkpoint_manager=manager,
        checkpoint_every_n_batches=spec.checkpoint_every,
        watchdog_timeout_s=spec.watchdog_timeout_s,
        step_builder=lambda opt: make_zero_train_step(
            model, loss_fn, opt, mesh, donate=False),
        gang_epoch=spec.gang_epoch)

    sharding = batch_sharding(mesh)
    nprocs = max(jax.process_count(), 1)

    def to_global(arr):
        per = arr.shape[0] // nprocs
        local = arr[rank * per:(rank + 1) * per] if nprocs > 1 else arr
        return jax.make_array_from_process_local_data(
            sharding, local, arr.shape)

    def factory():
        for x, y in job["batches"](spec.total_steps):
            yield (to_global(x), to_global(y))

    workdir = pathlib.Path(spec.workdir)
    hb_path = workdir / f"hb_{spec.gang_epoch}_{rank}.json"
    steps: List[int] = []
    losses: List[float] = []

    def handler(ev):
        if isinstance(ev, E.EndIteration):
            steps.append(ev.batch_id)
            losses.append(ev.cost)
            _atomic_json(hb_path, {"step": ev.batch_id,
                                   "t": time.time(),
                                   "pid": os.getpid()})

    preempted = False
    try:
        final = rt.run(state, factory, num_passes=1,
                       event_handler=handler)
        final_step = int(final.step)
    except Preempted as p:
        # teardown's SIGTERM landed at a step boundary: the drain save
        # is durable, the member exits clean and rejoins next epoch
        preempted = True
        final_step = p.step
    result = {
        "rank": rank,
        "gang_epoch": spec.gang_epoch,
        "restored_step": rt.restored_step,
        "final_step": final_step,
        "preempted": preempted,
        "steps": steps,
        "losses": losses,
        "counters": {k: float(v) for k, v in rt.counters().items()},
    }
    _atomic_json(workdir / f"result_{spec.gang_epoch}_{rank}.json",
                 result)
    return result


class GangSupervisor:
    """Elastic gang-of-processes trainer supervisor.

    Spawns `num_processes` gang members (each a fresh python process
    running `gang_child_main`), then watches two signals per member:
    its EXIT CODE and its heartbeat file (written after every step).
    Failure handling, in classification order:

    - **crashed** (exit not in {0, 75}): the member's host is gone —
      SIGKILL, OOM, segfault. The whole barrier is torn down (a gloo
      collective with a dead peer never completes; surviving members
      are blocked inside it, so SIGTERM → grace → SIGKILL) and the
      gang reforms at `previous - crashed` members.
    - **watchdog exit (75)**: the member's own progress deadline fired
      (train.resilience.Watchdog) — it is a HEALTHY host that detected
      a wedge. The still-alive members that stopped heartbeating are
      the wedged ones: they get fenced with a real SIGKILL
      (`fenced_wedged`), and only THEY count as lost.
    - **stale heartbeat, nobody dead**: a member is alive but not
      scheduling (SIGSTOP, pathological swap). A dead-or-wedged peer
      stalls everyone's heartbeats (they block in the next collective),
      so the victim is picked by direct evidence first — a process in
      the stopped state — falling back to the oldest heartbeat. The
      victim is fenced (SIGKILL), then the usual teardown/reform runs.

    Attribution policy: members lost = the ranks observed failed at the
    FIRST failing poll (fault injection waits on the victim's corpse,
    making this deterministic); later collateral exits during teardown
    are NOT lost members — their hosts rejoin the reformed gang.

    Every reform bumps `gang_epoch` (tagged on step spans and worker
    counters), picks a fresh coordinator port, renumbers ranks 0..M-1,
    and resumes from the newest durable checkpoint via the
    reshard-on-restore path. Below `min_procs`: `GangFailedError`.
    """

    def __init__(self, builder: str,
                 builder_kwargs: Optional[Dict[str, Any]] = None, *,
                 workdir: str, checkpoint_dir: str,
                 num_processes: int, total_steps: int,
                 checkpoint_every: int = 2, seed: int = 0,
                 min_procs: int = 1,
                 watchdog_timeout_s: Optional[float] = None,
                 heartbeat_timeout_s: float = 60.0,
                 boot_timeout_s: float = 300.0,
                 grace_s: float = 5.0, poll_s: float = 0.25,
                 pin_cpu: bool = True,
                 extra_env: Optional[Dict[str, str]] = None,
                 flight: Optional[Any] = None,
                 membership: Optional[Any] = None,
                 host_prefix: str = "gang"):
        if num_processes < 1 or min_procs < 1:
            raise ValueError("num_processes and min_procs must be >= 1")
        self.builder = builder
        self.builder_kwargs = dict(builder_kwargs or {})
        self.workdir = pathlib.Path(workdir)
        self.checkpoint_dir = checkpoint_dir
        self.num_processes = num_processes
        self.total_steps = total_steps
        self.checkpoint_every = checkpoint_every
        self.seed = seed
        self.min_procs = min_procs
        self.watchdog_timeout_s = watchdog_timeout_s
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.boot_timeout_s = boot_timeout_s
        self.grace_s = grace_s
        self.poll_s = poll_s
        self.pin_cpu = pin_cpu
        self.extra_env = dict(extra_env or {})
        self.flight = flight
        # optional membership mirror (MembershipService or -Client
        # duck type): each rank is a fake host `{prefix}-{rank}`; its
        # lease renews on observed heartbeats, and an EVICTION seen in
        # the view is a lost member — the teardown/reform path fires
        # from a view change, not only from a local waitpid
        self.membership = membership
        self.host_prefix = host_prefix
        self._member_creds: Dict[int, Tuple[int, int]] = {}
        self.membership_evictions = 0
        # ledger (registry-source shaped: numeric values only)
        self.gang_epoch = 0
        self.reforms = 0
        self.members_lost = 0
        self.fenced_wedged = 0
        self.watchdog_exits = 0
        self.spawned = 0
        # live gang. Staleness is lease-based (the shared
        # cluster.lease semantics): every member holds a lease that
        # starts on the boot budget and re-arms with the heartbeat
        # ttl each time the supervisor OBSERVES a fresh heartbeat
        # write — expiry is the one staleness verdict
        self.procs: Dict[int, subprocess.Popen] = {}
        self._hb_leases = LeaseTable(default_ttl_s=boot_timeout_s,
                                     clock=time.monotonic)
        self._hb_seen: Dict[int, Tuple] = {}
        self._logs: List[Any] = []

    # -- observability -----------------------------------------------------

    def counters(self) -> dict:
        return {
            "gang_epoch": self.gang_epoch,
            "reforms": self.reforms,
            "members_lost": self.members_lost,
            "fenced_wedged": self.fenced_wedged,
            "watchdog_exits": self.watchdog_exits,
            "spawned": self.spawned,
            "membership_evictions": self.membership_evictions,
            "active": sum(1 for p in self.procs.values()
                          if p.poll() is None),
        }

    def bind_metrics(self, registry, *, prefix: str = "train_gang",
                     labels: Optional[dict] = None) -> None:
        registry.register_source(prefix, self.counters, labels=labels)

    def member_heartbeat(self, rank: int) -> Optional[dict]:
        return _read_json(
            self.workdir / f"hb_{self.gang_epoch}_{rank}.json")

    # -- spawn / teardown --------------------------------------------------

    def _spawn(self, count: int) -> None:
        self.workdir.mkdir(parents=True, exist_ok=True)
        spec = GangSpec(
            builder=self.builder, builder_kwargs=self.builder_kwargs,
            checkpoint_dir=self.checkpoint_dir,
            workdir=str(self.workdir), total_steps=self.total_steps,
            checkpoint_every=self.checkpoint_every, seed=self.seed,
            coordinator=f"127.0.0.1:{_free_port()}",
            num_processes=count, gang_epoch=self.gang_epoch,
            watchdog_timeout_s=self.watchdog_timeout_s)
        spec_path = self.workdir / f"spec_{self.gang_epoch}.json"
        spec_path.write_text(spec.to_json())
        # children must pick their platform BEFORE distributed init:
        # scripts.cpu_guard pins cpu config-only (local gangs / CI);
        # pin_cpu=False leaves jax's TPU auto-detection alone
        prelude = "import scripts.cpu_guard; " if self.pin_cpu else ""
        code = (prelude + "from paddle_tpu.parallel.launch import "
                "gang_child_main; gang_child_main()")
        env = {k: v for k, v in os.environ.items()
               if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
        env["PYTHONPATH"] = (str(_REPO_ROOT) + os.pathsep
                             + env.get("PYTHONPATH", ""))
        env.update(self.extra_env)
        env["PADDLE_TPU_GANG_SPEC"] = str(spec_path)
        for rank in range(count):
            log_f = open(self.workdir
                         / f"log_{self.gang_epoch}_{rank}.txt", "w")
            self._logs.append(log_f)
            p = subprocess.Popen(
                [sys.executable, "-c", code],
                cwd=_REPO_ROOT,
                env={**env, "PADDLE_TPU_GANG_RANK": str(rank)},
                stdout=log_f, stderr=subprocess.STDOUT)
            self.procs[rank] = p
            self._hb_leases.grant(rank)     # the boot budget
            self.spawned += 1
        self._membership_register(count, spec.coordinator)

    def _teardown(self, reason: str) -> None:
        """SIGTERM (a member at a step boundary drains one save and
        exits clean) → grace → SIGKILL (members blocked in a dead
        collective never reach a boundary)."""
        if self.flight is not None and reason != "done":
            self.flight.record("fault", "gang-teardown",
                               reason=reason,
                               gang_epoch=self.gang_epoch)
        for p in self.procs.values():
            if p.poll() is None:
                try:
                    p.terminate()
                except OSError:
                    pass
        deadline = time.monotonic() + self.grace_s
        for p in self.procs.values():
            left = deadline - time.monotonic()
            try:
                p.wait(timeout=max(left, 0.1))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait(timeout=10)
        for f in self._logs:
            try:
                f.close()
            except OSError:
                pass
        self._logs.clear()
        self.procs.clear()
        self._hb_leases.clear()
        self._hb_seen.clear()
        self._membership_deregister()
        if self.flight is not None and reason != "done":
            self.flight.dump(str(self.workdir),
                             f"gang-teardown-{reason}",
                             extra={"counters": self.counters()})

    # -- failure detection -------------------------------------------------

    def _tick(self) -> None:
        """Per-poll hook; the fault-injection seam
        (`testing.faults.FaultPlan.wrap_gang` wraps it to deliver a
        real SIGKILL/SIGSTOP at an exact heartbeat step)."""

    @staticmethod
    def _proc_stopped(pid: int) -> bool:
        """Direct evidence of a SIGSTOPped/not-scheduling member
        (linux /proc state 'T'); False where /proc is unavailable —
        the oldest-heartbeat fallback picks the victim there."""
        try:
            with open(f"/proc/{pid}/stat") as f:
                return f.read().split(")")[-1].split()[0] in ("T", "t")
        except OSError:
            return False

    def _observe_heartbeats(self, ranks: List[int]) -> None:
        """Fold freshly WRITTEN heartbeats into lease renewals: a new
        (step, t) value proves the member progressed since the last
        poll, so its lease re-arms with the steady-state heartbeat
        ttl (the first heartbeat moves it off the boot budget). A
        fresh heartbeat is ground truth — if the lease lapsed only
        because the SUPERVISOR stalled past the ttl, it re-grants
        rather than declaring a progressing member stale."""
        for r in ranks:
            hb = self.member_heartbeat(r)
            booting = r not in self._hb_seen
            fresh = False
            if hb is not None:
                key = (hb.get("step"), hb.get("t"))
                if self._hb_seen.get(r) != key:
                    self._hb_seen[r] = key
                    fresh = True
                    if not self._hb_leases.renew(
                            r, ttl_s=self.heartbeat_timeout_s):
                        self._hb_leases.grant(
                            r, self.heartbeat_timeout_s)
            if fresh or (booting and hb is None):
                # membership mirrors liveness: progress renews, and a
                # still-booting member is alive by definition (its
                # boot budget is the local lease's concern)
                self._membership_renew(r)

    def _stale(self, rank: int) -> bool:
        return not self._hb_leases.alive(rank)

    # -- membership mirror (optional) --------------------------------------

    def _member_host(self, rank: int) -> str:
        return f"{self.host_prefix}-{rank}"

    def _membership_register(self, count: int,
                             coordinator: str) -> None:
        if self.membership is None:
            return
        for rank in range(count):
            try:
                r = self.membership.register(
                    self._member_host(rank),
                    {"rank": rank, "gang_epoch": self.gang_epoch,
                     "coordinator": coordinator},
                    ttl_s=self.heartbeat_timeout_s)
            except (OSError, ConnectionError, RuntimeError):
                return          # membership down: local paths still run
            self._member_creds[rank] = (r["token"], r["epoch"])

    def _membership_renew(self, rank: int) -> None:
        creds = self._member_creds.get(rank)
        if self.membership is None or creds is None:
            return
        token, epoch = creds
        try:
            resp = self.membership.renew(self._member_host(rank),
                                         token, epoch)
        except (OSError, ConnectionError, RuntimeError):
            return
        if resp["status"] == "ok":
            self._member_creds[rank] = (token, resp["epoch"])

    def _membership_lost(self, alive: List[int]) -> List[int]:
        """Ranks whose fake host has LEFT the membership view (lease
        expiry or external eviction) — host death arriving as a view
        change, the multi-host analog of a waitpid."""
        if self.membership is None:
            return []
        try:
            self.membership.tick()
            view = self.membership.view()
        except (OSError, ConnectionError, RuntimeError):
            return []
        return [r for r in alive
                if r in self._member_creds
                and self._member_host(r) not in view.hosts]

    def _membership_deregister(self) -> None:
        if self.membership is None:
            return
        for rank, (token, epoch) in list(self._member_creds.items()):
            try:
                self.membership.deregister(self._member_host(rank),
                                           token, epoch)
            except (OSError, ConnectionError, RuntimeError):
                pass            # eviction will reap it eventually
        self._member_creds.clear()

    def _fence(self, ranks: List[int]) -> None:
        for r in ranks:
            p = self.procs.get(r)
            if p is not None and p.poll() is None:
                try:
                    os.kill(p.pid, signal.SIGKILL)
                    p.wait(timeout=10)
                except OSError:
                    pass
            self.fenced_wedged += 1

    def _pick_wedged(self, alive: List[int]) -> List[int]:
        stopped = [r for r in alive
                   if self._proc_stopped(self.procs[r].pid)]
        if stopped:
            return stopped
        # oldest heartbeat: the victim stopped progressing FIRST; its
        # peers wrote at least one later heartbeat before blocking
        def hb_time(r):
            hb = self.member_heartbeat(r)
            return hb.get("t", 0.0) if hb else 0.0
        return [min(alive, key=hb_time)] if alive else []

    def _monitor(self, deadline_s: float) -> Tuple[str, List[int]]:
        """Poll until the gang finishes ("done") or loses members
        ("lost", ranks). Raises GangFailedError on the deadline."""
        t0 = time.monotonic()
        while True:
            if time.monotonic() - t0 > deadline_s:
                raise GangFailedError(
                    f"gang epoch {self.gang_epoch} made no outcome "
                    f"within {deadline_s:.0f}s")
            self._tick()
            codes = {r: p.poll() for r, p in self.procs.items()}
            alive = [r for r, c in codes.items() if c is None]
            self._observe_heartbeats(alive)
            crashed = [r for r, c in codes.items()
                       if c not in (None, 0, 75)]
            wd = [r for r, c in codes.items() if c == 75]
            if crashed:
                return "lost", crashed
            if wd:
                self.watchdog_exits += len(wd)
                victims = self._pick_wedged(
                    [r for r in alive if self._stale(r)] or alive)
                self._fence(victims)
                return "lost", victims
            if not alive:
                return "done", []
            stale = [r for r in alive if self._stale(r)]
            if stale:
                victims = self._pick_wedged(stale)
                self._fence(victims)
                return "lost", victims
            evicted = self._membership_lost(alive)
            if evicted:
                # the view says these hosts are GONE: fence locally
                # and reform at the surviving count, exactly like a
                # local staleness verdict
                self.membership_evictions += len(evicted)
                self._fence(evicted)
                return "lost", evicted
            time.sleep(self.poll_s)

    # -- drive -------------------------------------------------------------

    def run(self, *, deadline_s: float = 600.0) -> dict:
        """Drive the job to completion through any number of reforms.
        Returns {"results": [per-rank result dicts of the FINAL
        epoch], "counters": ...}."""
        t0 = time.monotonic()
        count = self.num_processes
        while True:
            self._spawn(count)
            try:
                outcome, lost = self._monitor(
                    deadline_s - (time.monotonic() - t0))
            except BaseException:
                self._teardown("error")
                raise
            if outcome == "done":
                epoch = self.gang_epoch
                self._teardown("done")
                results = []
                for rank in range(count):
                    rec = _read_json(
                        self.workdir / f"result_{epoch}_{rank}.json")
                    if rec is not None:
                        results.append(rec)
                return {"results": results,
                        "counters": self.counters()}
            self._teardown(f"lost-{sorted(lost)}")
            self.members_lost += len(lost)
            count -= len(lost)
            if count < self.min_procs:
                raise GangFailedError(
                    f"{len(lost)} member(s) lost at epoch "
                    f"{self.gang_epoch}; {count} survivors is below "
                    f"min_procs={self.min_procs}")
            self.reforms += 1
            self.gang_epoch += 1
