"""JAX API compatibility shims for the parallel stack.

`shard_map` moved twice across JAX releases: it grew up in
`jax.experimental.shard_map.shard_map` (where the replication-check
kwarg is spelled `check_rep`) and graduated to `jax.shard_map` (where
the same kwarg is `check_vma`). The parallel modules (sparse,
ring_attention, pipeline, moe, collectives) target the graduated API;
this shim lets them run unmodified on environments that only ship the
experimental one — the tier-1 CPU env among them — instead of failing
at first call with AttributeError.

One definition on purpose: every shard_map call in this package routes
through here, so a third relocation is a one-line fix.
"""

from __future__ import annotations

import jax

__all__ = ["shard_map", "axis_size", "pcast", "memory_kind"]


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kw):
    """`jax.shard_map` with graceful fallback to the experimental API.

    `check_vma` follows the graduated spelling; on the experimental
    API it is forwarded as `check_rep` (same semantics: disable the
    per-output replication/varying-axes check for bodies whose
    collectives the checker cannot type)."""
    impl = getattr(jax, "shard_map", None)
    if impl is not None:
        if check_vma is not None:
            kw["check_vma"] = check_vma
        return impl(f, mesh=mesh, in_specs=in_specs,
                    out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as exp_impl

    if check_vma is not None:
        kw["check_rep"] = check_vma
    return exp_impl(f, mesh=mesh, in_specs=in_specs,
                    out_specs=out_specs, **kw)


def axis_size(axis):
    """`jax.lax.axis_size` where it exists; the classic
    `psum(1, axis)` idiom (constant-folded to a Python int at trace
    time) everywhere else. Call inside shard_map/pmap only."""
    import jax.lax as lax

    impl = getattr(lax, "axis_size", None)
    if impl is not None:
        return impl(axis)
    return lax.psum(1, axis)


def pcast(x, axis, *, to):
    """`jax.lax.pcast` (varying-axes retyping for the shard_map vma
    checker) where it exists; `lax.pvary` on the releases that only
    have the one-way cast; identity on releases with neither — those
    predate the vma type system entirely, so there is nothing to
    retype and the value is already correct."""
    import jax.lax as lax

    impl = getattr(lax, "pcast", None)
    if impl is not None:
        return impl(x, axis, to=to)
    pvary = getattr(lax, "pvary", None)
    if pvary is not None and to == "varying":
        return pvary(x, axis)
    return x


def memory_kind(device, kind):
    """`kind` when `device` can address that memory space, else None
    (= the device's default space). XLA:CPU has no pinned_host/device
    kinds, only unpinned_host — shardings built with the TPU kinds
    must degrade rather than fail at device_put."""
    try:
        kinds = {m.kind for m in device.addressable_memories()}
    except Exception:
        return None
    return kind if kind in kinds else None
