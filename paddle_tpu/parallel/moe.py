"""Mixture-of-experts with expert parallelism over the mesh.

No reference counterpart (acmol/Paddle predates MoE); this extends the
framework's "EP" story beyond sparse embeddings (parallel/sparse.py) to
sparsely-activated FFNs, the modern TPU workload the mesh design exists
for. Design follows the GShard/Switch dispatch shape — chosen because
it is the MXU-native formulation:

- top-k softmax router with an auxiliary load-balancing loss;
- FIXED expert capacity C (static shapes — XLA requirement), tokens
  over capacity are dropped (their combine weight is zero, the
  residual stream carries them through unchanged);
- dispatch/combine are one-hot einsums — big batched matmuls instead
  of scatter/gather, which is exactly what the MXU wants;
- expert parallelism: experts sharded over the mesh `model` axis, the
  dispatched [E, C, D] block exchanged with ONE tiled all_to_all each
  way over ICI (the same exchange shape as sparse.alltoall_lookup).

Parity of intent: the reference scaled sparse models by sharding
embedding rows across pservers; this shards expert FFNs across chips.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.parallel import compat

from paddle_tpu.core.mesh import MODEL_AXIS
from paddle_tpu.nn import initializers


class MoEOutput(NamedTuple):
    y: jnp.ndarray          # [T, D] combined expert outputs
    aux_loss: jnp.ndarray   # scalar load-balancing loss
    dropped: jnp.ndarray    # scalar fraction of tokens over capacity


def init_moe_params(rng, n_experts: int, d_model: int, d_ff: int,
                    dtype=jnp.float32):
    """Stacked expert FFNs + router. Expert weights are [E, ...] so one
    einsum runs every expert; shard axis 0 over the mesh for EP."""
    k_r, k_1, k_2 = jax.random.split(rng, 3)
    smart = initializers.smart_uniform()
    w1 = jnp.stack([smart(k, (d_model, d_ff))
                    for k in jax.random.split(k_1, n_experts)]).astype(dtype)
    w2 = jnp.stack([smart(k, (d_ff, d_model))
                    for k in jax.random.split(k_2, n_experts)]).astype(dtype)
    return {
        "router": {"kernel": initializers.normal(0.02)(
            k_r, (d_model, n_experts)).astype(dtype)},
        "w1": w1, "b1": jnp.zeros((n_experts, d_ff), dtype),
        "w2": w2, "b2": jnp.zeros((n_experts, d_model), dtype),
    }


def shard_moe_params(params, mesh: Mesh, *, axis: str = MODEL_AXIS):
    """Expert-shard the stacked weights over `axis` (router replicated)."""
    e = params["w1"].shape[0]
    if e % mesh.shape[axis] != 0:
        raise ValueError(f"{e} experts not divisible by {axis} axis size "
                         f"{mesh.shape[axis]}")
    def put(x, s):
        return jax.device_put(x, NamedSharding(mesh, s))

    return {
        "router": {"kernel": put(params["router"]["kernel"], P())},
        "w1": put(params["w1"], P(axis)), "b1": put(params["b1"], P(axis)),
        "w2": put(params["w2"], P(axis)), "b2": put(params["b2"], P(axis)),
    }


def capacity_for(n_tokens: int, n_experts: int,
                 capacity_factor: float = 1.25, k: int = 1, *,
                 multiple: int = 4) -> int:
    """Static per-expert capacity: factor * k * tokens/experts, rounded
    up to `multiple` (sublane-friendly). Top-k routing makes k*T
    assignments, so capacity must scale with k or even perfectly
    balanced routing drops (k-1)/k of the assignments (GShard sizes
    capacity the same way)."""
    raw = max(1, int(capacity_factor * k * n_tokens / n_experts))
    return -(-raw // multiple) * multiple


class Routing(NamedTuple):
    """Index-form routing: per round r < k and token t, token t goes to
    `expert[r, t]` slot `slot[r, t]` with weight `gate[r, t]` (0 when
    dropped). Linear in T — the dense [T, E, C] tensors are derived
    views for small shapes (top_k_gating)."""
    expert: jnp.ndarray    # [k, T] int32
    slot: jnp.ndarray      # [k, T] int32
    keep: jnp.ndarray      # [k, T] bool
    gate: jnp.ndarray      # [k, T] f32, kept-renormalized per token
    aux_loss: jnp.ndarray  # scalar
    dropped: jnp.ndarray   # scalar


def top_k_routing(router_logits, k: int, capacity: int, *,
                  rng: Optional[jax.Array] = None, jitter: float = 0.0,
                  token_mask=None) -> Routing:
    """Top-k expert assignment with fixed capacity, in index form.

    router_logits: [T, E]. token_mask: optional [T] bool — False
    positions (padding) claim NO capacity slots, contribute nothing to
    the aux loss, and don't count as dropped.

    aux_loss is the Switch/GShard load-balancing term: E * sum_e
    (token_fraction_e * mean_router_prob_e) — 1.0 at perfect balance.
    Position within each expert's capacity is assigned in token order
    (cumsum over the one-hot), over-capacity assignments get gate 0.
    """
    t, e = router_logits.shape
    if rng is not None and jitter > 0.0:
        router_logits = router_logits * jax.random.uniform(
            rng, router_logits.shape, router_logits.dtype,
            1.0 - jitter, 1.0 + jitter)
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    if token_mask is None:
        valid = jnp.ones((t,), jnp.float32)
    else:
        valid = token_mask.astype(jnp.float32)

    # claimed[e] tokens already routed to expert e by earlier choices
    claimed = jnp.zeros((e,), jnp.int32)
    masked = probs
    first_mask = None
    kept_any = jnp.zeros((t,), bool)
    experts, slots, keeps, gates = [], [], [], []
    for _ in range(k):
        gate = jnp.max(masked, axis=-1) * valid              # [T]
        choice = jnp.argmax(masked, axis=-1)                 # [T]
        onehot = jax.nn.one_hot(choice, e, dtype=jnp.float32) \
            * valid[:, None]                                 # pads claim 0
        if first_mask is None:
            first_mask = onehot
        # position of each token in its chosen expert's buffer
        pos = (jnp.cumsum(onehot, axis=0) - onehot) + claimed[None, :]
        pos_tok = jnp.sum(pos * onehot, axis=-1).astype(jnp.int32)  # [T]
        keep = (pos_tok < capacity) & (valid > 0)
        kept_any = kept_any | keep
        experts.append(choice.astype(jnp.int32))
        slots.append(jnp.minimum(pos_tok, capacity - 1))
        keeps.append(keep)
        gates.append(gate * keep.astype(jnp.float32))
        claimed = claimed + jnp.sum(
            onehot * keep[:, None].astype(jnp.float32), axis=0).astype(
                jnp.int32)
        masked = masked * (1.0 - onehot)                      # next choice

    gate_kt = jnp.stack(gates)                                # [k, T]
    # renormalize over the KEPT gates so each surviving token's weights
    # sum to 1 (dropped assignments are excluded from the mass)
    denom = jnp.sum(gate_kt, axis=0, keepdims=True)
    gate_kt = jnp.where(denom > 0, gate_kt / jnp.maximum(denom, 1e-9), 0.0)

    n_valid = jnp.maximum(jnp.sum(valid), 1.0)
    frac_tokens = jnp.sum(first_mask, axis=0) / n_valid       # [E]
    mean_prob = jnp.sum(probs * valid[:, None], axis=0) / n_valid  # [E]
    aux = e * jnp.sum(frac_tokens * mean_prob)
    dropped = 1.0 - jnp.sum(kept_any.astype(jnp.float32) * valid) / n_valid
    return Routing(jnp.stack(experts), jnp.stack(slots), jnp.stack(keeps),
                   gate_kt, aux, dropped)


class ECRouting(NamedTuple):
    """Expert-choice routing (Zhou et al. 2022): each EXPERT picks its
    top-`capacity` tokens. token_idx[e, c] is the token filling expert
    e's slot c; gate[e, c] its combine weight (0 for masked padding)."""
    token_idx: jnp.ndarray  # [E, C] int32
    gate: jnp.ndarray       # [E, C] f32
    dropped: jnp.ndarray    # scalar: fraction of valid tokens no expert picked


def expert_choice_routing(router_logits, capacity: int, *,
                          token_mask=None) -> ECRouting:
    """Every expert slot fills (perfect load balance, no aux loss
    needed); a token can be picked by several experts or none (residual
    carries unpicked tokens). Dispatch is a pure gather, combine a
    scatter-add — no capacity bookkeeping at all."""
    t, e = router_logits.shape
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    if token_mask is not None:
        probs = probs * token_mask.astype(jnp.float32)[:, None]
    gate, token_idx = jax.lax.top_k(probs.T, capacity)    # [E, C] each
    picked = jnp.zeros((t,), bool).at[token_idx.reshape(-1)].set(
        True, mode="drop")
    valid = jnp.ones((t,), bool) if token_mask is None else token_mask
    n_valid = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
    dropped = 1.0 - jnp.sum((picked & valid).astype(jnp.float32)) / n_valid
    return ECRouting(token_idx.astype(jnp.int32), gate, dropped)


def expert_choice_ffn(params, x, *, capacity_factor: float = 2.0,
                      token_mask=None,
                      activation=jax.nn.gelu) -> MoEOutput:
    """MoE FFN under expert-choice routing. x: [T, D]. Capacity per
    expert = capacity_factor * T / E (the paper's formulation; factor 2
    means each token is used twice on average)."""
    t, d = x.shape
    e = params["w1"].shape[0]
    # an expert can never take more tokens than exist — decode steps
    # (t = batch) and short prefills would otherwise ask top_k for more
    # entries than the token axis holds
    cap = min(capacity_for(t, e, capacity_factor), t)
    logits = x @ params["router"]["kernel"]
    r = expert_choice_routing(logits, cap, token_mask=token_mask)
    expert_in = jnp.take(x, r.token_idx.reshape(-1), axis=0) \
        .reshape(e, cap, d)                               # pure gather
    out = _expert_ffn(params, expert_in, activation)
    weighted = (r.gate[..., None] * out.astype(jnp.float32)) \
        .reshape(e * cap, d)
    y = jnp.zeros((t, d), jnp.float32).at[r.token_idx.reshape(-1)] \
        .add(weighted)                                    # scatter combine
    return MoEOutput(y.astype(x.dtype), jnp.zeros((), jnp.float32),
                     r.dropped)


def top_k_gating(router_logits, k: int, capacity: int, *,
                 rng: Optional[jax.Array] = None, jitter: float = 0.0,
                 token_mask=None):
    """Dense [T, E, C] dispatch/combine tensors derived from
    top_k_routing — O(T*E*C) memory, intended for small shapes and
    tests; the compute paths use the index form or the einsum dispatch
    chosen by _use_scatter. Returns (dispatch, combine, aux_loss,
    dropped_frac)."""
    t, e = router_logits.shape
    r = top_k_routing(router_logits, k, capacity, rng=rng, jitter=jitter,
                      token_mask=token_mask)
    dispatch, combine = _dense_from_routing(r, e, capacity)
    return dispatch, combine, r.aux_loss, r.dropped


def _dense_from_routing(r: Routing, e: int, capacity: int):
    eo = jax.nn.one_hot(r.expert, e, dtype=jnp.float32) \
        * r.keep[..., None]                                   # [k, T, E]
    so = jax.nn.one_hot(r.slot, capacity, dtype=jnp.float32) \
        * r.keep[..., None]                                   # [k, T, C]
    sel = eo[:, :, :, None] * so[:, :, None, :]               # [k, T, E, C]
    dispatch = jnp.sum(sel, axis=0)
    combine = jnp.sum(r.gate[:, :, None, None] * sel, axis=0)
    return dispatch, combine


# element-count ceiling for materializing the dense [T, E, C] dispatch
# tensor (einsum dispatch feeds the MXU best at small/medium shapes; at
# LM shapes C grows with T so the tensor is quadratic in T and must be
# avoided — 2^24 f32 elements = 64 MiB)
_EINSUM_DISPATCH_MAX = 1 << 24


def _use_scatter(impl: str, t: int, e: int, cap: int) -> bool:
    if impl == "auto":
        return t * e * cap > _EINSUM_DISPATCH_MAX
    if impl in ("scatter", "einsum"):
        return impl == "scatter"
    raise ValueError(f"dispatch_impl must be auto|einsum|scatter, got {impl}")


def _dispatch_expert_in(routing: Routing, x, e: int, cap: int, impl: str):
    """[E, C, D] expert inputs via the impl chosen by _use_scatter.
    Returns (expert_in, dense_combine_or_None) — the dense combine is
    reused by _combine_out when the einsum path was taken."""
    t = x.shape[0]
    if _use_scatter(impl, t, e, cap):
        return scatter_dispatch(routing, x, e, cap), None
    dispatch, combine = _dense_from_routing(routing, e, cap)
    ein = jnp.einsum("tec,td->ecd", dispatch,
                     x.astype(jnp.float32)).astype(x.dtype)
    return ein, combine


def _combine_out(routing: Routing, dense_combine, out_ecd, cap: int):
    """Per-token combine matching _dispatch_expert_in's chosen impl."""
    if dense_combine is None:
        return gather_combine(routing, out_ecd, cap)
    return jnp.einsum("tec,ecd->td", dense_combine,
                      out_ecd.astype(jnp.float32))


def scatter_dispatch(routing: Routing, x, n_experts: int, capacity: int):
    """Build [E, C, D] expert inputs by scatter-add — O(k*T + E*C*D)
    memory (the einsum dispatch materializes [T, E, C], quadratic in T
    since C grows with T; at LM shapes that tensor is GBs)."""
    k, t = routing.expert.shape
    d = x.shape[-1]
    flat = routing.expert * capacity + routing.slot           # [k, T]
    # dropped assignments -> index E*C, written into a dump row
    flat = jnp.where(routing.keep, flat, n_experts * capacity)
    buf = jnp.zeros((n_experts * capacity + 1, d), x.dtype)
    xs = jnp.broadcast_to(x, (k, t, d)).reshape(k * t, d)
    buf = buf.at[flat.reshape(-1)].add(xs)
    return buf[:-1].reshape(n_experts, capacity, d)


def gather_combine(routing: Routing, expert_out, capacity: int):
    """Combine [E, C, D] expert outputs back per token: y[t] = sum_r
    gate[r,t] * out[expert[r,t], slot[r,t]] — gates are 0 for dropped
    assignments, so any gathered row there is discarded."""
    e, c, d = expert_out.shape
    flat_out = expert_out.reshape(e * c, d).astype(jnp.float32)
    flat = routing.expert * capacity + routing.slot           # [k, T]
    picked = jnp.take(flat_out, flat.reshape(-1), axis=0)     # [k*T, D]
    picked = picked.reshape(*flat.shape, d)                   # [k, T, D]
    return jnp.sum(routing.gate[..., None] * picked, axis=0)  # [T, D]


def _expert_ffn(params, x, activation):
    """x: [E_local, C', D] -> [E_local, C', D] via the stacked weights."""
    h = jnp.einsum("ecd,edf->ecf", x, params["w1"]) + params["b1"][:, None, :]
    h = activation(h)
    return jnp.einsum("ecf,efd->ecd", h, params["w2"]) + params["b2"][:, None, :]


def moe_ffn(params, x, *, k: int = 2, capacity_factor: float = 1.25,
            rng=None, jitter: float = 0.0, token_mask=None,
            activation=jax.nn.gelu,
            dispatch_impl: str = "auto") -> MoEOutput:
    """Single-device MoE FFN. x: [T, D] (flatten [B, S, D] first).
    token_mask [T] bool: padding positions neither claim capacity nor
    bias the aux loss. dispatch_impl: "einsum" (one-hot matmuls,
    materializes [T, E, C]) vs "scatter" (linear-memory scatter/gather);
    "auto" picks by the dense tensor's size."""
    t, d = x.shape
    e = params["w1"].shape[0]
    cap = capacity_for(t, e, capacity_factor, k)
    logits = x @ params["router"]["kernel"]
    routing = top_k_routing(logits, k, cap, rng=rng, jitter=jitter,
                            token_mask=token_mask)
    expert_in, dense_combine = _dispatch_expert_in(routing, x, e, cap,
                                                   dispatch_impl)
    expert_out = _expert_ffn(params, expert_in, activation)
    y = _combine_out(routing, dense_combine, expert_out, cap)
    return MoEOutput(y.astype(x.dtype), routing.aux_loss, routing.dropped)


def make_expert_parallel_ffn(mesh: Mesh, *, axis: str = MODEL_AXIS,
                             data_axis: Optional[str] = None,
                             k: int = 2, capacity_factor: float = 1.25,
                             jitter: float = 0.0,
                             activation=jax.nn.gelu,
                             dispatch_impl: str = "auto"):
    """Build an expert-parallel MoE FFN over `mesh`.

    Tokens arrive sharded over BOTH mesh axes (or replicated when
    `data_axis` is None); experts are sharded over `axis`
    (shard_moe_params). Each shard routes its local tokens, dispatches
    into [E, C_loc, D], then ONE tiled all_to_all regroups the block so
    every shard holds its OWN experts' tokens from ALL shards; the FFN
    runs batched over local experts; the mirrored all_to_all brings
    results home for the local combine. Per-step ICI volume is
    2 * E * C_loc * D — the K*D shape of sparse.alltoall_lookup, with
    matmul dispatch instead of sorts.

    The token axis is split over (data_axis, axis) jointly: if it were
    split over data_axis alone, every `axis` peer would hold the same
    tokens, compute the same routing, and the exchange would carry
    n_model identical copies — n_model-fold redundant expert FLOPs and
    ICI traffic. With the joint split each peer's C_loc block is
    distinct tokens and the exchange volume claim above is real.

    Returns fn(params, x [T, D], rng=None) -> MoEOutput with y sharded
    like x. T must divide by data_axis_size * axis_size (static
    shapes).
    """
    n_exp_shards = mesh.shape[axis]
    dspec = P((data_axis, axis)) if data_axis else P()

    def body(params, x, rng):
        t_loc, d = x.shape
        e_loc = params["w1"].shape[0]
        e = e_loc * n_exp_shards  # global expert count
        cap = capacity_for(t_loc, e, capacity_factor, k)
        logits = x @ params["router"]["kernel"]
        if data_axis is not None:
            # distinct jitter noise per token shard (both mesh axes)
            rng = jax.random.fold_in(
                rng, lax.axis_index(data_axis) * n_exp_shards
                + lax.axis_index(axis))
        routing = top_k_routing(logits, k, cap, rng=rng, jitter=jitter)
        aux, dropped = routing.aux_loss, routing.dropped
        if data_axis is None:
            # tokens replicated: every shard computes identical routing,
            # so exchanging dispatch buffers would move (and compute on)
            # n identical copies. Run only the LOCAL experts'
            # assignments and psum the partial combines — zero
            # all-to-all, 1/n the expert FLOPs.
            shard = lax.axis_index(axis)
            local_e = routing.expert - shard * e_loc
            in_range = (local_e >= 0) & (local_e < e_loc) & routing.keep
            r_loc = routing._replace(
                expert=jnp.clip(local_e, 0, e_loc - 1),
                keep=in_range,
                gate=routing.gate * in_range.astype(jnp.float32))
            local_in, dense_c = _dispatch_expert_in(r_loc, x, e_loc, cap,
                                                    dispatch_impl)
            out = _expert_ffn(params, local_in, activation)
            y = _combine_out(r_loc, dense_c, out, cap)
            y = lax.psum(y, axis).astype(x.dtype)
            return MoEOutput(y, aux, dropped)
        # local dispatch against ALL experts: [E, C, D]
        expert_in, combine = _dispatch_expert_in(routing, x, e, cap,
                                                 dispatch_impl)
        # regroup: shard j receives its local experts' buffers from all
        # shards -> [E_loc * n, C, D] == concat over source shards
        recv = lax.all_to_all(expert_in, axis, split_axis=0, concat_axis=0,
                              tiled=True)
        # run local experts over the concatenated capacity blocks:
        # [n * E_loc, C, D] -> group to [E_loc, n * C, D]
        grouped = recv.reshape(n_exp_shards, e_loc, cap, d).swapaxes(0, 1) \
            .reshape(e_loc, n_exp_shards * cap, d)
        out = _expert_ffn(params, grouped, activation)
        # mirror the reshape + exchange to bring tokens home
        back = out.reshape(e_loc, n_exp_shards, cap, d).swapaxes(0, 1) \
            .reshape(n_exp_shards * e_loc, cap, d)
        home = lax.all_to_all(back, axis, split_axis=0, concat_axis=0,
                              tiled=True)                     # [E, C, D]
        y = _combine_out(routing, combine, home, cap).astype(x.dtype)
        aux = lax.pmean(aux, (data_axis, axis))
        dropped = lax.pmean(dropped, (data_axis, axis))
        return MoEOutput(y, aux, dropped)

    pspec = {"router": {"kernel": P()},
             "w1": P(axis), "b1": P(axis), "w2": P(axis), "b2": P(axis)}
    fn = compat.shard_map(
        body, mesh=mesh,
        in_specs=(pspec, dspec, P()),
        out_specs=MoEOutput(dspec, P(), P()),
        check_vma=False,
    )

    def apply(params, x, rng=None):
        if rng is None:
            rng = jax.random.key(0)
        return fn(params, x, rng)

    return apply
