"""Long-context attention parallelism: ring attention + Ulysses all-to-all.

The reference's long-sequence story is padding-free LoD batching unrolled
frame-by-frame (reference: gserver/layers/SequenceToBatch.h:41,
RecurrentGradientMachine.cpp:428-775) — memory-linear in sequence length
with no sequence sharding. The TPU-native build makes sequence/context
parallelism first-class instead: shard the time dimension over the mesh
`seq` axis and compute exact attention with

  * ring attention — K/V shards rotate around the `seq` ring via
    `lax.ppermute` while each device keeps its Q shard; a streaming
    (flash-style) softmax merges per-block partial results, so no device
    ever materialises the full [T, T] score matrix or the full K/V.
  * Ulysses all-to-all — `lax.all_to_all` re-shards [T/n, H] -> [T, H/n]
    so each device runs full-sequence attention over a head subset, then
    shards back; cheaper per step on small meshes, needs H % n == 0.

Both are exact (up to fp reassociation) and differentiable; tests compare
against the dense reference on an 8-device CPU mesh.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu.parallel import compat

from paddle_tpu.core.mesh import SEQ_AXIS

NEG_INF = -1e30


def dense_attention(q, k, v, *, causal: bool = False, mask=None):
    """Reference dense attention. q,k,v: [B, T, H, D] -> [B, T, H, D].

    `mask`: optional [B, Tq, Tk] boolean, True = attend. Scores and
    softmax run in f32 whatever the compute dtype (the models' shared
    attention invariant).
    """
    d = q.shape[-1]
    acc_dtype = jnp.promote_types(jnp.float32, q.dtype)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=acc_dtype) / jnp.sqrt(
        d).astype(acc_dtype)
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        cm = (jnp.arange(tq, dtype=jnp.int32)[:, None]
              >= jnp.arange(tk, dtype=jnp.int32)[None, :])
        scores = jnp.where(cm[None, None], scores, NEG_INF)
    if mask is not None:
        scores = jnp.where(mask[:, None], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v,
                      preferred_element_type=acc_dtype).astype(q.dtype)


def _block_attend(q, k, v, q_offset, k_offset, *, causal, scale):
    """Partial attention of a Q block against one K/V block.

    Returns (o, l, m): un-normalised output [B,Tq,H,D], row sum l and row
    max m [B,Tq,H] — the flash-attention streaming-softmax statistics.
    """
    # scores/exp/sums in >=f32 regardless of the compute dtype — the
    # same invariant as the models' dense attention (bf16 running
    # exp-sums degrade with sequence length and break CP==dense parity)
    acc_dtype = jnp.promote_types(jnp.float32, q.dtype)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=acc_dtype) \
        * scale.astype(acc_dtype)
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        qpos = q_offset + jnp.arange(tq, dtype=jnp.int32)
        kpos = k_offset + jnp.arange(tk, dtype=jnp.int32)
        cm = qpos[:, None] >= kpos[None, :]
        scores = jnp.where(cm[None, None], scores, NEG_INF)
    m = jnp.max(scores, axis=-1)  # [B,H,Tq]
    p = jnp.exp(scores - m[..., None])
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                   preferred_element_type=acc_dtype)
    # -> [B,Tq,H] layout for the running stats
    return o, l.transpose(0, 2, 1), m.transpose(0, 2, 1)


def _merge(acc, blk):
    """Merge streaming-softmax partials (o, l, m) from two blocks."""
    o1, l1, m1 = acc
    o2, l2, m2 = blk
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    # stats are [B,Tq,H]; broadcast over the trailing D of the outputs
    o = o1 * a1[..., None] + o2 * a2[..., None]
    l = l1 * a1 + l2 * a2
    return o, l, m


def ring_attention(q, k, v, *, axis: str = SEQ_AXIS, causal: bool = False):
    """Exact attention with sequence sharded over the `axis` ring.

    Call INSIDE shard_map. q,k,v: per-shard [B, T_local, H, D] (the global
    sequence is the concatenation over the axis, in axis-index order).
    K/V blocks rotate around the ring once; a streaming softmax merges
    block partials, so peak memory is O(T_local^2) scores per device.
    """
    n = compat.axis_size(axis)
    idx = jax.lax.axis_index(axis)
    t_local = q.shape[1]
    scale = (1.0 / jnp.sqrt(q.shape[-1])).astype(q.dtype)
    q_offset = idx * t_local

    def step(carry, _):
        kb, vb, src, acc = carry
        k_offset = src * t_local
        blk = _block_attend(q, kb, vb, q_offset, k_offset,
                            causal=causal, scale=scale)
        acc = _merge(acc, blk)
        # rotate k/v one step around the ring: shard j -> shard j+1, so
        # after s steps this device holds the block of device (idx - s).
        perm = [(i, (i + 1) % n) for i in range(n)]
        kb = jax.lax.ppermute(kb, axis, perm)
        vb = jax.lax.ppermute(vb, axis, perm)
        src = (src - 1) % n
        return (kb, vb, src, acc), None

    b, _, h, d_ = q.shape
    # accumulators match _block_attend's >=f32 partials
    acc_dtype = jnp.promote_types(jnp.float32, q.dtype)
    zero = (
        jnp.zeros((b, t_local, h, d_), acc_dtype),
        jnp.zeros((b, t_local, h), acc_dtype),
        jnp.full((b, t_local, h), NEG_INF, acc_dtype),
    )
    (kb, vb, src, acc), _ = jax.lax.scan(
        step, (k, v, idx, zero), None, length=n)
    o, l, _ = acc
    return (o / l[..., None]).astype(q.dtype)


def ulysses_attention(q, k, v, *, axis: str = SEQ_AXIS,
                      causal: bool = False):
    """Ulysses-style attention: all-to-all seq-shard -> head-shard.

    Call INSIDE shard_map with per-shard [B, T_local, H, D]; needs
    H % axis_size == 0. Each device sees the FULL sequence for H/n heads,
    runs dense attention, and all-to-alls back to sequence sharding.
    """
    n = compat.axis_size(axis)
    # [B, T/n, H, D] -> gather seq, split heads -> [B, T, H/n, D]
    qh = jax.lax.all_to_all(q, axis, split_axis=2, concat_axis=1, tiled=True)
    kh = jax.lax.all_to_all(k, axis, split_axis=2, concat_axis=1, tiled=True)
    vh = jax.lax.all_to_all(v, axis, split_axis=2, concat_axis=1, tiled=True)
    oh = dense_attention(qh, kh, vh, causal=causal)
    return jax.lax.all_to_all(oh, axis, split_axis=1, concat_axis=2,
                              tiled=True)


def make_sequence_parallel_attention(
    mesh: Mesh,
    *,
    kind: str = "ring",
    causal: bool = False,
    batch_axis: Optional[str] = None,
    axis: str = SEQ_AXIS,
):
    """Build a jit-able whole-array attention fn sharded over `axis`.

    Takes global [B, T, H, D] arrays; shard_map internally shards T over
    the seq axis (and optionally B over `batch_axis`).
    """
    if kind == "ring":
        inner = ring_attention
    elif kind == "ulysses":
        inner = ulysses_attention
    else:
        raise ValueError(f"unknown kind {kind!r}: expected 'ring' or 'ulysses'")
    spec = P(batch_axis, axis, None, None)

    @functools.partial(
        compat.shard_map, mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=spec, check_vma=False)
    def fn(q, k, v):
        return inner(q, k, v, axis=axis, causal=causal)

    return fn
