"""Trainer-side parameter-server client: failover, epochs, adapter.

The counterpart of `native.pserver` (reference:
trainer/RemoteParameterUpdater.cpp + go/pserver/client — trainers talk
to every shard, pull touched rows, push sparse gradients, and survive
server death via etcd re-discovery; here the replacement discovery is
the `ShardSpec` endpoint list, primary first). Three layers:

- `ShardConn`: one shard's socket, hardened exactly like
  `native.MasterClient` — default timeout on every op, exponential
  backoff with seeded jitter, a fresh socket per attempt (a timeout
  mid-frame desyncs the framing; the old socket is never reused). On
  top of that: **failover** — a connection that cannot even be
  ESTABLISHED advances to the next endpoint (primary died → replica),
  while a mid-flight send/recv failure retries the SAME endpoint first
  (a lost ACK from a live server must be re-asked there, where the
  epoch watermark answers DUP).
- `PServerClient`: routes rows to owning shards by the `ShardSpec` row
  ranges (the `shard_rows` layout), numbers every push with a per-shard
  monotonic epoch so ANY retry — reconnect, failover, lost ACK — is
  applied exactly once server-side, and transparently re-registers when
  a push/finish lands on a server that never saw this trainer's lease
  (the failover target, or a server that expired us).
- `PServerEmbedding`: the swap-in adapter for the existing sparse call
  sites — same `init / lookup / apply_row_grads / alltoall_lookup /
  alltoall_push_row_grads` surface as `ShardedEmbedding` and
  `HostOffloadEmbedding`, with the table living server-side (the
  "table" argument is an opaque handle), so `ResilientTrainer` keeps
  training through a killed shard.
"""

from __future__ import annotations

import random as _random
import socket
import struct
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu.native.pserver import (
    OP_FINISH_PASS,
    OP_GET_ROWS,
    OP_HEARTBEAT,
    OP_LOAD,
    OP_PASS_STATE,
    OP_PUSH,
    OP_REGISTER,
    OP_STATS,
    OP_WATERMARK,
    ST_DUP,
    ST_LEASE_EXPIRED,
    ST_OK,
    ShardSpec,
)
from paddle_tpu.wire import recv_frame, send_frame


class PServerError(RuntimeError):
    """A shard answered with a server-side error (protocol misuse or an
    internal failure) — distinct from ConnectionError, which means no
    answer arrived at all."""


class ShardConn:
    """Failover socket client for ONE shard's endpoint chain.

    `call()` walks a bounded backoff schedule; endpoint choice is
    sticky (keep talking to whoever answered last). Failure handling
    follows where the failure happened:

    - connect refused/timeout: the endpoint is DOWN — advance to the
      next one immediately (primary → replica failover);
    - send/recv failure on an established connection: the server may be
      alive and may have APPLIED the op (lost ACK) — reconnect the SAME
      endpoint once so the retry lands where the epoch watermark can
      answer DUP; only if it cannot be re-established does the chain
      advance.

    Every pserver op is safe to retry through this path: reads are
    idempotent, pushes carry epochs (server dedupes), register re-grants
    and finish_pass re-marks.
    """

    def __init__(self, endpoints: Sequence[Tuple[str, int]], *,
                 timeout: float = 30.0, retries: int = 8,
                 backoff_base: float = 0.02, backoff_max: float = 1.0,
                 seed: Optional[int] = None):
        if not endpoints:
            raise ValueError("ShardConn needs at least one endpoint")
        self.endpoints = [tuple(e) for e in endpoints]
        self.timeout = timeout
        self.retries = retries
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self._rng = _random.Random(seed)
        self._active = 0
        self._sock: Optional[socket.socket] = None
        self._closed = False
        # failover ledger: bumped every time the chain advances to a
        # different endpoint. A caching reader snapshots this to learn
        # "the answering server may have changed" — the conservative
        # re-validate trigger (chain replication keeps a backup a
        # PREFIX of its primary, so a failover can legally rewind the
        # watermark; rows filled from the old primary must not be
        # trusted against the new authority).
        self.failovers = 0

    @property
    def active_endpoint(self) -> Tuple[str, int]:
        return self.endpoints[self._active]

    def _advance(self) -> None:
        self._active = (self._active + 1) % len(self.endpoints)
        self.failovers += 1

    def _connect(self) -> None:
        sock = socket.create_connection(self.active_endpoint,
                                        timeout=self.timeout)
        try:
            sock.settimeout(self.timeout)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except BaseException:
            sock.close()
            raise
        self._sock = sock

    def _drop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _backoff(self, attempt: int) -> float:
        ceiling = min(self.backoff_base * (2 ** attempt),
                      self.backoff_max)
        return self._rng.uniform(0, ceiling) or ceiling / 2

    def call(self, payload: bytes) -> bytes:
        if self._closed:
            raise RuntimeError("ShardConn is closed")
        last: Optional[BaseException] = None
        same_endpoint_retry = False
        ok = False
        try:
            for attempt in range(self.retries + 1):
                if attempt:
                    time.sleep(self._backoff(attempt - 1))
                try:
                    if self._sock is None:
                        self._connect()
                except (ConnectionError, socket.timeout, OSError) as e:
                    # endpoint down: fail over along the chain
                    last = e
                    self._advance()
                    same_endpoint_retry = False
                    continue
                try:
                    send_frame(self._sock, payload)
                    resp = recv_frame(self._sock)
                    ok = True
                    return resp
                except (ConnectionError, socket.timeout, OSError) as e:
                    last = e
                    self._drop()
                    # mid-flight failure: one fresh-socket retry on the
                    # SAME endpoint (lost-ACK / restarted server), then
                    # fail over
                    if same_endpoint_retry:
                        self._advance()
                        same_endpoint_retry = False
                    else:
                        same_endpoint_retry = True
        finally:
            if not ok:
                self._drop()
        raise ConnectionError(
            f"no pserver endpoint of {self.endpoints} answered after "
            f"{self.retries + 1} attempts: {last}") from last

    def update_endpoints(self,
                         endpoints: Sequence[Tuple[str, int]]) -> None:
        """Re-point the chain (membership topology refresh). The live
        socket drops so the next call dials the new chain from its
        head — primary first, per the inventory's ordering."""
        if not endpoints:
            raise ValueError("ShardConn needs at least one endpoint")
        self.endpoints = [tuple(e) for e in endpoints]
        self._active = 0
        self._drop()
        self.failovers += 1    # new chain == possibly-new authority

    def close(self) -> None:
        self._closed = True
        self._drop()


def shard_specs_from_view(view) -> List[ShardSpec]:
    """Resolve the pserver tier's `ShardSpec` list from a membership
    `ClusterView`: each serving host folds
    ``{"shards": [{"shard_id", "row_lo", "row_hi", "endpoints":
    [[host, port], ...], "role": "primary"|"backup"}, ...]}`` into its
    inventory, and this merges them per shard — primary endpoints
    first (the failover chain's head), then backups, each group in
    host_id order. The hardcoded-endpoint-list constructor stays for
    single-box runs; the multi-host path resolves HERE."""
    by_shard: dict = {}
    for host_id in sorted(view.hosts):
        for entry in view.hosts[host_id].get("shards", ()):
            rec = by_shard.setdefault(
                int(entry["shard_id"]),
                {"row_lo": int(entry["row_lo"]),
                 "row_hi": int(entry["row_hi"]),
                 "primary": [], "backup": []})
            if (rec["row_lo"], rec["row_hi"]) != (
                    int(entry["row_lo"]), int(entry["row_hi"])):
                raise ValueError(
                    f"hosts disagree on shard {entry['shard_id']} row "
                    f"range — a stale inventory is still registered")
            role = entry.get("role", "primary")
            eps = [(e[0], int(e[1])) for e in entry["endpoints"]]
            rec["backup" if role == "backup" else "primary"].extend(eps)
    specs = []
    for sid in sorted(by_shard):
        rec = by_shard[sid]
        endpoints = rec["primary"] + rec["backup"]
        if not endpoints:
            raise ValueError(f"shard {sid} has no endpoints in view")
        specs.append(ShardSpec(shard_id=sid, row_lo=rec["row_lo"],
                               row_hi=rec["row_hi"],
                               endpoints=endpoints))
    return specs


class PServerClient:
    """One trainer's connection fabric to every shard of a sparse table.

    `trainer_id` must be unique per trainer process — it keys both the
    lease and the exactly-once epoch watermark. Pushes are serialized
    per shard by `_lock` (the epoch order IS the apply order)."""

    def __init__(self, specs: Sequence[ShardSpec], dim: int, *,
                 trainer_id: int = 0,
                 lease_ttl_s: float = 30.0, timeout: float = 30.0,
                 retries: int = 8, backoff_base: float = 0.02,
                 backoff_max: float = 1.0, seed: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.dim = int(dim)
        specs = sorted(specs, key=lambda s: s.row_lo)
        for a, b in zip(specs, specs[1:]):
            if a.row_hi != b.row_lo:
                raise ValueError(
                    f"shard specs leave a row gap/overlap at "
                    f"[{a.row_hi}, {b.row_lo})")
        if not specs or specs[0].row_lo != 0:
            raise ValueError("shard specs must start at row 0")
        self.specs = specs
        self.num_rows = specs[-1].row_hi
        self.trainer_id = trainer_id
        self.lease_ttl_s = lease_ttl_s
        self._bounds = np.asarray([s.row_hi for s in specs], np.int64)
        self._conns = [ShardConn(s.endpoints, timeout=timeout,
                                 retries=retries,
                                 backoff_base=backoff_base,
                                 backoff_max=backoff_max,
                                 seed=None if seed is None else seed + i)
                       for i, s in enumerate(specs)]
        self._tokens: List[Optional[int]] = [None] * len(specs)
        self._epochs = [0] * len(specs)
        # last applied-update watermark each shard reported on ANY reply
        # (get_rows, push ACK, explicit probe) — the freshness ledger the
        # embed-cache invalidation protocol reads. A value can REGRESS
        # after failover (a backup is a prefix of its primary); consumers
        # of on_watermark must treat a rewind as "re-validate everything".
        self.watermarks = [0] * len(specs)
        # seam: fires as (shard, new_wm, prev_wm) after any reply carries
        # a watermark, inside the client lock — the subscriber (the
        # tiered cache) must only touch its own state, never call back
        # into this client
        self.on_watermark: Optional[Callable[[int, int, int], None]] = None
        # REENTRANT: every public RPC entry point takes it (the
        # heartbeat thread shares the per-shard sockets with the caller
        # — an unlocked send/recv pair would desync the framing), and
        # public methods compose (fetch_table -> get_rows)
        self._lock = threading.RLock()
        self.clock = clock
        self._last_hb = clock()
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        self.stats = {"pushes": 0, "duplicate_acks": 0,
                      "reregistrations": 0, "pulls": 0,
                      "watermark_polls": 0}
        # observability seam (the PagePool.obs_hook idiom): fires AFTER
        # an RPC settles, exceptions swallowed — ResilientTrainer points
        # this at the live step span so push/pull land on its trail.
        self.obs_hook: Optional[Callable] = None

    @classmethod
    def from_membership(cls, membership, dim: int,
                        **kw) -> "PServerClient":
        """Build a client whose shard topology comes from the
        membership view instead of a hardcoded endpoint list (the
        multi-host path). The membership handle is kept so
        `refresh_topology` can re-resolve after a view change."""
        client = cls(shard_specs_from_view(membership.view()), dim, **kw)
        client._membership = membership
        return client

    def refresh_topology(self) -> bool:
        """Re-resolve shard endpoints from the current membership view
        and re-point each shard's failover chain. The shard LAYOUT
        (count + row ranges) must be unchanged — rows don't move when a
        backup takes over, only endpoints do. Returns True if any
        chain actually changed. Raises RuntimeError when the client
        was not built via `from_membership`."""
        membership = getattr(self, "_membership", None)
        if membership is None:
            raise RuntimeError(
                "refresh_topology needs a membership-backed client "
                "(use PServerClient.from_membership)")
        fresh = shard_specs_from_view(membership.view())
        with self._lock:
            if [(s.shard_id, s.row_lo, s.row_hi) for s in fresh] != \
                    [(s.shard_id, s.row_lo, s.row_hi) for s in self.specs]:
                raise ValueError(
                    "membership view changed the shard layout; "
                    "rebuild the client instead of refreshing it")
            changed = False
            for i, spec in enumerate(fresh):
                if spec.endpoints != self._conns[i].endpoints:
                    self._conns[i].update_endpoints(spec.endpoints)
                    self.specs[i].endpoints = list(spec.endpoints)
                    changed = True
        if changed:
            self._obs("pserver.topology_refresh",
                      shards=len(fresh))
        return changed

    def _obs(self, event: str, **ctx) -> None:
        if self.obs_hook is None:
            return
        try:
            self.obs_hook(event, ctx)
        except Exception:
            pass

    def bind_metrics(self, registry, *, prefix: str = "pserver_client",
                     labels=None) -> None:
        """Register this client's exactly-once ledger as a read-through
        metrics source — exported numbers ARE the ledger."""
        registry.register_source(prefix, lambda: dict(self.stats),
                                 labels=labels)

    # -- leases ----------------------------------------------------------

    def register(self) -> None:
        with self._lock:
            for s in range(len(self.specs)):
                self._register_shard(s)

    # locklint: holds-lock(callers enter via public methods holding
    # the reentrant self._lock)
    def _register_shard(self, s: int) -> None:
        resp = self._conns[s].call(
            bytes([OP_REGISTER])
            + struct.pack("<qd", self.trainer_id, self.lease_ttl_s))
        self._check(resp, "register")
        token, _pass, watermark = struct.unpack_from("<QqQ", resp, 1)
        self._tokens[s] = token
        # adopt the server's applied-epoch watermark: a RESTARTED
        # trainer (fresh client, epochs at 0) must number its next push
        # PAST what the shard already applied, or every push until the
        # watermark would be silently DUP-discarded. max() keeps an
        # in-flight retried epoch valid on failover re-registration.
        self._epochs[s] = max(self._epochs[s], int(watermark))

    def heartbeat(self) -> None:
        """Renew every shard lease; a shard that no longer knows us
        (expired, or a failover target) gets a fresh registration."""
        with self._lock:
            for s in range(len(self.specs)):
                if self._tokens[s] is None:
                    self._register_shard(s)
                    continue
                resp = self._conns[s].call(
                    bytes([OP_HEARTBEAT])
                    + struct.pack("<qQ", self.trainer_id,
                                  self._tokens[s]))
                if resp[0] == ST_LEASE_EXPIRED:
                    self.stats["reregistrations"] += 1
                    self._register_shard(s)
                else:
                    self._check(resp, "heartbeat")
            self._last_hb = self.clock()

    def start_heartbeats(self, interval_s: float) -> None:
        if self._hb_thread is not None:
            return
        self._hb_stop.clear()

        def loop():
            while not self._hb_stop.wait(interval_s):
                try:
                    self.heartbeat()
                except (ConnectionError, PServerError):
                    pass    # next RPC surfaces a real outage

        self._hb_thread = threading.Thread(
            target=loop, name="pserver-heartbeat", daemon=True)
        self._hb_thread.start()

    # -- routing ---------------------------------------------------------

    def _owner_of(self, ids: np.ndarray) -> np.ndarray:
        """Owning-shard index per id; invalid ids map to -1 (zero rows
        on read, dropped on push — the padding-id contract shared with
        sharded_lookup / masked_row_delta)."""
        owner = np.searchsorted(self._bounds, ids, side="right")
        owner[(ids < 0) | (ids >= self.num_rows)] = -1
        return owner

    def owner_of(self, ids) -> np.ndarray:
        """Public routing map (the cache's shard-stamping entry point):
        [K] global ids -> [K] owning shard index, -1 for out-of-range."""
        return self._owner_of(
            np.ascontiguousarray(np.asarray(ids).reshape(-1), np.int64))

    @property
    def n_shards(self) -> int:
        return len(self.specs)

    # locklint: holds-lock(called from get_rows/_push_shard/
    # poll_watermarks under the reentrant self._lock)
    def _note_watermark(self, s: int, wm: int) -> None:
        prev = self.watermarks[s]
        self.watermarks[s] = wm
        hook = self.on_watermark
        if hook is not None and wm != prev:
            try:
                hook(s, wm, prev)
            except Exception:
                pass    # observability seam, never the data plane

    def shard_failovers(self) -> List[int]:
        """Per-shard count of chain advances (endpoint changes) so far —
        a caching reader diffs consecutive snapshots to detect "a
        different server may be answering now" and re-validates."""
        with self._lock:
            return [c.failovers for c in self._conns]

    def poll_watermarks(self) -> List[int]:
        """One OP_WATERMARK probe per shard: refresh the freshness
        ledger without moving any row bytes. This is the bounded-
        staleness heartbeat for an all-hit cache (misses and pushes
        refresh the ledger for free on their own replies)."""
        with self._lock:
            for s in range(len(self.specs)):
                resp = self._conns[s].call(bytes([OP_WATERMARK]))
                self._check(resp, "watermark")
                (wm,) = struct.unpack_from("<Q", resp, 1)
                self._note_watermark(s, int(wm))
            self.stats["watermark_polls"] += 1
            return list(self.watermarks)

    # -- data plane ------------------------------------------------------

    def get_rows(self, ids) -> np.ndarray:
        """[K] global ids -> [K, D] rows; out-of-range ids give ZERO
        vectors (sharded_lookup's contract)."""
        ids = np.ascontiguousarray(np.asarray(ids).reshape(-1), np.int64)
        dim = self.dim
        out = np.zeros((ids.shape[0], dim), np.float32)
        owner = self._owner_of(ids)
        with self._lock:
            for s in range(len(self.specs)):
                sel = np.flatnonzero(owner == s)
                if sel.size == 0:
                    continue
                sub = np.ascontiguousarray(ids[sel])
                resp = self._conns[s].call(
                    bytes([OP_GET_ROWS]) + struct.pack("<I", sub.size)
                    + sub.tobytes())
                self._check(resp, "get_rows")
                n, wm = struct.unpack_from("<IQ", resp, 1)
                rows = np.frombuffer(resp, np.float32, n * dim,
                                     offset=13).reshape(n, dim)
                out[sel] = rows
                self._note_watermark(s, int(wm))
            self.stats["pulls"] += 1
        self._obs("pserver_pull", rows=int(ids.shape[0]))
        return out

    def push_row_grads(self, ids, row_grads, lr: float) -> None:
        """Route sparse row gradients to their owning shards, exactly
        once each: every per-shard push gets the next epoch, and the
        retry loop (reconnect, failover, lost ACK) re-sends the SAME
        epoch until some replica ACKs — OK (applied now) and DUP
        (applied earlier, ACK lost) are both success."""
        ids = np.ascontiguousarray(np.asarray(ids).reshape(-1), np.int64)
        grads = np.ascontiguousarray(np.asarray(row_grads, np.float32))
        if grads.shape != (ids.shape[0], self.dim):
            raise ValueError(f"row_grads shape {grads.shape} != "
                             f"({ids.shape[0]}, {self.dim})")
        owner = self._owner_of(ids)
        with self._lock:
            for s in range(len(self.specs)):
                sel = np.flatnonzero(owner == s)
                if sel.size == 0:
                    continue
                self._epochs[s] += 1
                self._push_shard(s, self._epochs[s],
                                 np.ascontiguousarray(ids[sel]),
                                 np.ascontiguousarray(grads[sel]), lr)

    # locklint: holds-lock(called from push_row_grads/load_table
    # under the reentrant self._lock)
    def _push_shard(self, s: int, epoch: int, ids: np.ndarray,
                    grads: np.ndarray, lr: float) -> None:
        payload = (bytes([OP_PUSH])
                   + struct.pack("<qQdI", self.trainer_id, epoch, lr,
                                 ids.size)
                   + ids.tobytes() + grads.tobytes())
        while True:
            if self._tokens[s] is None:
                self._register_shard(s)
            resp = self._conns[s].call(payload)
            if resp[0] in (ST_OK, ST_DUP) and len(resp) >= 9:
                # push ACKs carry the post-apply shard watermark — the
                # pushing process's cache invalidates without a probe
                (wm,) = struct.unpack_from("<Q", resp, 1)
                self._note_watermark(s, int(wm))
            if resp[0] == ST_OK:
                self.stats["pushes"] += 1
                self._obs("pserver_push", shard=s, epoch=epoch,
                          rows=int(ids.size), outcome="ok")
                return
            if resp[0] == ST_DUP:
                # applied on an earlier attempt whose ACK was lost —
                # exactly-once held, count it for observability
                self.stats["duplicate_acks"] += 1
                self._obs("pserver_push", shard=s, epoch=epoch,
                          rows=int(ids.size), outcome="dup")
                return
            if resp[0] == ST_LEASE_EXPIRED:
                # the answering server (failover target, or one that
                # expired us) has no lease for this trainer: register
                # there and re-send the SAME epoch
                self.stats["reregistrations"] += 1
                self._tokens[s] = None
                continue
            self._check(resp, "push")

    # -- pass barrier ----------------------------------------------------

    def finish_pass(self, *, wait: bool = True, poll_s: float = 0.01,
                    timeout_s: float = 60.0) -> int:
        """Vote this trainer's pass finished on every shard; with
        `wait`, block until each shard's pass counter advances past its
        pre-vote value (all live-leased trainers finished — an expired
        peer is released by its lease, so a dead trainer cannot wedge
        this barrier). Returns the new pass number of shard 0.

        The poll loop does NOT hold the client lock between polls (the
        heartbeat thread must keep running under a long barrier) and
        renews this trainer's own leases every `lease_ttl_s / 3` while
        waiting — a waiting trainer must never expire out of the very
        barrier it is waiting on. A vote lives on the server that took
        it: if this shard's lease TOKEN changes mid-wait (failover to
        the replica, or an expiry + re-registration), the vote is gone
        there — the loop detects the token change and RE-VOTES on the
        now-active server, rebasing its target on that server's pass
        counter."""
        with self._lock:
            start = [self._finish_shard(s)
                     for s in range(len(self.specs))]
            vote_tokens = list(self._tokens)
        if not wait:
            return start[0][0] + (1 if start[0][1] else 0)
        deadline = self.clock() + timeout_s
        pass_nums = []
        for s, (before, done) in enumerate(start):
            target = before + 1
            current = before + 1 if done else before
            while current < target:
                if self.clock() > deadline:
                    raise TimeoutError(
                        f"pass barrier on shard {s} not reached in "
                        f"{timeout_s}s (pass {current} < {target})")
                time.sleep(poll_s)
                if (self.clock() - self._last_hb
                        > self.lease_ttl_s / 3):
                    self.heartbeat()
                with self._lock:
                    if self._tokens[s] != vote_tokens[s]:
                        # new lease => new server or fresh registration:
                        # our vote did not travel — re-assert it and
                        # rebase on that server's own counter
                        before, done = self._finish_shard(s)
                        vote_tokens[s] = self._tokens[s]
                        target = before + 1
                        current = before + 1 if done else before
                        continue
                current = self.pass_state(s)
            pass_nums.append(current)
        return pass_nums[0]

    def pass_state(self, s: int = 0) -> int:
        """Shard `s`'s current pass number (also ticks its lease-expiry
        sweep — any RPC does)."""
        with self._lock:
            resp = self._conns[s].call(bytes([OP_PASS_STATE]))
        self._check(resp, "pass_state")
        return struct.unpack_from("<q", resp, 1)[0]

    # locklint: holds-lock(called from finish_pass's locked poll loop)
    def _finish_shard(self, s: int) -> Tuple[int, bool]:
        while True:
            if self._tokens[s] is None:
                self._register_shard(s)
            resp = self._conns[s].call(
                bytes([OP_FINISH_PASS])
                + struct.pack("<qQ", self.trainer_id, self._tokens[s]))
            if resp[0] == ST_LEASE_EXPIRED:
                self.stats["reregistrations"] += 1
                self._tokens[s] = None
                continue
            self._check(resp, "finish_pass")
            pass_num, = struct.unpack_from("<q", resp, 1)
            done = bool(resp[9])
            # pass_num is POST-advance when done; report pre-vote base
            return (pass_num - 1, True) if done else (pass_num, False)

    # -- table init / dump ----------------------------------------------

    def load_table(self, table, *, chunk_rows: int = 8192) -> None:
        """SET the full table across shards (once-only init — the
        FinishInitParams analog). Idempotent; replicates to backups."""
        table = np.ascontiguousarray(np.asarray(table, np.float32))
        if table.shape != (self.num_rows, self.dim):
            raise ValueError(f"table shape {table.shape} != "
                             f"({self.num_rows}, {self.dim})")
        with self._lock:
            for s, spec in enumerate(self.specs):
                for lo in range(spec.row_lo, spec.row_hi, chunk_rows):
                    hi = min(lo + chunk_rows, spec.row_hi)
                    resp = self._conns[s].call(
                        bytes([OP_LOAD])
                        + struct.pack("<qI", lo, hi - lo)
                        + table[lo:hi].tobytes())
                    self._check(resp, "load")

    def fetch_table(self, *, chunk_rows: int = 8192) -> np.ndarray:
        """Assemble the full [num_rows, dim] table from the shards (for
        checks and exports — row traffic, not a hot path)."""
        out = np.zeros((self.num_rows, self.dim), np.float32)
        for lo in range(0, self.num_rows, chunk_rows):
            hi = min(lo + chunk_rows, self.num_rows)
            out[lo:hi] = self.get_rows(np.arange(lo, hi, dtype=np.int64))
        return out

    def shard_stats(self) -> List[dict]:
        import json

        stats = []
        with self._lock:
            for c in self._conns:
                resp = c.call(bytes([OP_STATS]))
                self._check(resp, "stats")
                stats.append(json.loads(resp[1:].decode()))
        return stats

    # -- plumbing --------------------------------------------------------

    @staticmethod
    def _check(resp: bytes, what: str) -> None:
        if not resp:
            raise PServerError(f"{what}: empty response")
        if resp[0] not in (ST_OK, ST_DUP):
            if resp[0] == ST_LEASE_EXPIRED:
                raise PServerError(f"{what}: lease expired (register "
                                   f"first)")
            raise PServerError(f"{what}: {resp[1:].decode(errors='replace')}")

    def close(self) -> None:
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5)
            self._hb_thread = None
        for c in self._conns:
            c.close()

    def __enter__(self) -> "PServerClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class PServerEmbedding:
    """Embedding adapter whose table lives on the parameter-server tier.

    Same call surface as `ShardedEmbedding`/`HostOffloadEmbedding`
    (init / lookup / apply_row_grads + the alltoall_* aliases the CTR
    call sites use), so it swaps into existing models: the dense update
    stays wherever it was (sharded on-chip), the sparse tail trains
    through `PServerClient` — and therefore through shard death,
    failover and retry, with exactly-once row updates.

    The `table` argument threaded through the surface is an opaque
    handle (the real rows are server-side); it is returned unchanged by
    the update ops so functional-style call sites keep composing.
    """

    class Handle:
        """Opaque stand-in for the on-device table."""

        def __init__(self, vocab: int, dim: int):
            self.shape = (vocab, dim)

        def __repr__(self):
            return f"PServerEmbedding.Handle{self.shape}"

    def __init__(self, client: PServerClient, *, init_scale: float = 0.01,
                 name: str = "pserver_embedding"):
        self.client = client
        self.vocab = client.num_rows
        self.dim = client.dim
        self.init_scale = init_scale
        self.name = name

    def init(self, rng) -> "PServerEmbedding.Handle":
        """Generate the table host-side (numpy seeded from the jax key,
        the HostOffloadEmbedding idiom — a pserver-scale table must
        never materialize in device memory) and LOAD it onto the
        shards; replication carries it to the backups."""
        import jax

        seed = np.asarray(jax.random.key_data(rng)).ravel()
        host_rng = np.random.default_rng([int(s) for s in seed])
        table = (host_rng.standard_normal(
            (self.vocab, self.dim), np.float32) * self.init_scale)
        self.client.load_table(table)
        return PServerEmbedding.Handle(self.vocab, self.dim)

    def lookup(self, table, ids):
        """ids [K] -> [K, D] rows on device; out-of-range ids (e.g. -1
        padding) give ZERO vectors — the shared sparse-lookup contract."""
        import jax.numpy as jnp

        rows = self.client.get_rows(np.asarray(ids))
        return jnp.asarray(rows)

    def apply_row_grads(self, table, ids, row_grads, lr):
        self.client.push_row_grads(np.asarray(ids),
                                   np.asarray(row_grads), lr)
        return table

    # aliases matching the ShardedEmbedding call sites
    def alltoall_lookup(self, table, ids, *, capacity=None,
                        return_overflow: bool = False):
        out = self.lookup(table, ids)
        if return_overflow:
            import jax.numpy as jnp

            return out, jnp.zeros((), jnp.int32)
        return out

    def alltoall_push_row_grads(self, table, ids, row_grads, lr, *,
                                capacity=None):
        return self.apply_row_grads(table, ids, row_grads, lr)

    # -- cache-backing surface (parallel.sparse.LookupSurface) ---------

    def pull_rows(self, table, ids):
        """Host-side read-through entry point for the tiered cache:
        [K] ids -> ([K, D] float32 host rows, per-shard watermark list
        as of each shard's reply). One RPC per owning shard per call —
        the batched miss-fill contract."""
        rows = self.client.get_rows(np.asarray(ids))
        return rows, list(self.client.watermarks)

    def owner_of(self, ids) -> np.ndarray:
        return self.client.owner_of(ids)

    @property
    def n_shards(self) -> int:
        return self.client.n_shards

    def poll_watermarks(self, table):
        return self.client.poll_watermarks()

    def shard_failovers(self):
        return self.client.shard_failovers()
