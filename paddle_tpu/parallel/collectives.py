"""Named communication primitives over the device mesh.

The TPU-native replacement for the reference's three comm stacks — the
block-sharded parameter-server RPC (reference: pserver/ParameterServer2.h:341
sendParameter/addGradient), the Go pserver's SendGrad/GetParam (reference:
go/pserver/service.go:285,311), and Fluid's NCCL ops (reference:
operators/nccl_op.cu.cc:41-209 ncclAllReduce/Reduce/Bcast). On TPU every
one of those wire exchanges is an XLA collective over ICI/DCN; this
module names them with the reference's semantics:

  all_reduce_sum/mean  — addGradient + op_SGD barrier round trip
  all_gather           — getParameter broadcast of fresh values
  reduce_scatter       — ZeRO-style sharded-optimizer grad exchange
  all_to_all           — sparse/embedding row exchange (getParameterSparse)
  ppermute_ring        — MultiGradientMachine's neighbor ring copy
  broadcast_from       — parameter-init broadcast (FinishInitParams)

Each primitive has (a) an in-context form for use inside shard_map
(operates on per-shard values, names the mesh axis), and (b) a
whole-array convenience wrapper that builds the shard_map itself.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.parallel import compat

from paddle_tpu.core.mesh import DATA_AXIS

# ---- in-context primitives (call inside shard_map) ----

def all_reduce_sum(x, axis: str = DATA_AXIS):
    return jax.lax.psum(x, axis_name=axis)


def all_reduce_mean(x, axis: str = DATA_AXIS):
    return jax.lax.pmean(x, axis_name=axis)


def all_gather(x, axis: str = DATA_AXIS, *, tiled: bool = True):
    return jax.lax.all_gather(x, axis_name=axis, tiled=tiled)


def reduce_scatter(x, axis: str = DATA_AXIS, *, scatter_dimension: int = 0):
    return jax.lax.psum_scatter(
        x, axis_name=axis, scatter_dimension=scatter_dimension, tiled=True)


def all_to_all(x, axis: str = DATA_AXIS, *, split_axis: int = 0,
               concat_axis: int = 0):
    return jax.lax.all_to_all(
        x, axis_name=axis, split_axis=split_axis, concat_axis=concat_axis,
        tiled=True)


def ppermute_ring(x, axis: str = DATA_AXIS, *, shift: int = 1):
    """Rotate shards around the ring by `shift` (reference:
    MultiGradientMachine.h:61-95 neighbor-thread ring copy)."""
    n = compat.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis_name=axis, perm=perm)


def axis_index(axis: str = DATA_AXIS):
    return jax.lax.axis_index(axis)


# ---- whole-array wrappers (build the shard_map for you) ----

def _shmap(mesh: Mesh, fn, in_spec: P, out_spec: P):
    return compat.shard_map(fn, mesh=mesh, in_specs=(in_spec,),
                         out_specs=out_spec)


def device_all_reduce_mean(x, mesh: Mesh, axis: str = DATA_AXIS):
    """Mean-reduce an axis-sharded array's shards (the sync-SGD gradient
    exchange as one call)."""
    fn = _shmap(mesh, lambda s: all_reduce_mean(s, axis), P(axis), P(axis))
    return fn(x)


def device_broadcast_from(x, mesh: Mesh, axis: str = DATA_AXIS,
                          source: int = 0):
    """Replicate shard `source`'s value to every device along `axis`
    (reference: FinishInitParams once-only init broadcast,
    go/pserver/service.go:260)."""

    def body(s):
        idx = jax.lax.axis_index(axis)
        n = compat.axis_size(axis)
        mask = (idx == source).astype(s.dtype)
        return jax.lax.psum(s * mask, axis_name=axis)

    fn = _shmap(mesh, body, P(axis), P())
    # drop the leading shard axis the P(axis) input implies: input is
    # [n*k, ...] sharded; output replicated [k, ...] from shard `source`
    return fn(x)


def replicate(x, mesh: Mesh):
    return jax.device_put(x, NamedSharding(mesh, P()))
