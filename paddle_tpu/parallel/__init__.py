"""Mesh parallelism: sharding rules, pjit train steps, collectives."""

from paddle_tpu.parallel.sharding import (
    make_param_shardings,
    batch_sharding,
    zero_shardings,
    MEGATRON_RULES,
)
from paddle_tpu.parallel.train_step import (
    aot_compile_train_step,
    make_sharded_train_step,
    make_zero_train_step,
    opt_state_bytes_per_replica,
    shard_train_state,
    train_state_shardings,
    zero_init_opt_state,
    zero_opt_shardings,
    zero_state_shardings,
    zero_true_sizes,
)
from paddle_tpu.parallel import collectives
from paddle_tpu.parallel import blocked_matmul
from paddle_tpu.parallel.blocked_matmul import (
    blocked_matmul as make_blocked_matmul,
    collective_matmul,
    matmul_reference,
    ring_matmul_gather,
    ring_matmul_reduce,
    stream_matmul,
    tp_dense,
)
# NB: the bare in-shard_map `ring_attention` fn stays on the submodule —
# re-exporting it here would shadow the `parallel.ring_attention` module.
from paddle_tpu.parallel.ring_attention import (
    dense_attention,
    make_sequence_parallel_attention,
    ulysses_attention,
)
from paddle_tpu.parallel.sparse import (
    HostOffloadEmbedding,
    ShardedEmbedding,
    alltoall_lookup,
    alltoall_push_row_grads,
    rowwise_sgd_update,
    shard_rows,
    sharded_embedding_bag,
    sharded_lookup,
    unique_rows_grad,
)
from paddle_tpu.parallel.pserver_client import (
    PServerClient,
    PServerEmbedding,
    PServerError,
    ShardConn,
)
from paddle_tpu.parallel import distributed
from paddle_tpu.parallel import launch
from paddle_tpu.parallel.launch import (
    GangFailedError,
    GangSpec,
    GangSupervisor,
    gang_child_main,
    run_gang_worker,
)
from paddle_tpu.parallel import moe
from paddle_tpu.parallel.moe import (
    expert_choice_ffn,
    init_moe_params,
    make_expert_parallel_ffn,
    moe_ffn,
    shard_moe_params,
)
from paddle_tpu.parallel import pipeline
from paddle_tpu.parallel.pipeline import (
    make_pipeline_forward,
    make_pipeline_train_step,
    shard_stage_params,
    stack_stage_params,
)
