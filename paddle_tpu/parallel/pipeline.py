"""Pipeline parallelism over a `pipe` mesh axis (GPipe-style).

The reference has NO pipeline engine (SURVEY §2.8: interleaved pipeline
absent — its model parallelism is device-pinned layers,
ParallelNeuralNetwork.cpp); this is the TPU-native extra that completes
the mesh-axis family {data, model, seq, PIPE}: S homogeneous stages'
parameters live stacked on a leading axis sharded over `pipe` (each
device holds ONE stage), microbatches stream through a lax.scan over
ticks with lax.ppermute handing activations to the next stage — the
compiler-friendly pipelining idiom (static shapes, no host control
flow). Backward is jax autodiff through the scan+ppermute program
(ppermute's transpose is the reverse permute), giving a GPipe-schedule
training step without hand-written reverse plumbing.

Constraints (standard for stacked-stage pipelining): all stages share
one structure/shape (e.g. N identical residual/transformer blocks), and
the activation shape is constant across stages.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.parallel import blocked_matmul, compat

PIPE_AXIS = "pipe"


def stack_stage_params(per_stage_params) -> dict:
    """Stack a list of S identical-structure param pytrees into one
    pytree with leading dim S (shard it P('pipe') via
    shard_stage_params)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def _stage_spec(x, axis: str, tp_axis: Optional[str]):
    """PartitionSpec for one stacked-stage leaf: always the stage dim
    over `axis`; with tensor parallelism on, matrix leaves (ndim >= 3:
    [S, K, N]) additionally shard their CONTRACTING dim over `tp_axis`
    (the row-parallel layout `blocked_matmul.tp_dense` consumes) while
    vector leaves (biases) stay replicated over tp."""
    if tp_axis is not None and x.ndim >= 3:
        return P(axis, tp_axis, *([None] * (x.ndim - 2)))
    return P(axis, *([None] * (x.ndim - 1)))


def shard_stage_params(stacked, mesh: Mesh, axis: str = PIPE_AXIS,
                       tp_axis: Optional[str] = None):
    """Place the stacked stage params so each pipe device holds its own
    stage's slice (and, with `tp_axis`, each tp device its weight-row
    block)."""
    return jax.tree.map(
        lambda x: jax.device_put(
            x, NamedSharding(mesh, _stage_spec(x, axis, tp_axis))),
        stacked)


def make_pipeline_forward(stage_fn: Callable, mesh: Mesh, *,
                          axis: str = PIPE_AXIS,
                          tp_axis: Optional[str] = None,
                          tp_overlap: bool = True):
    """Build fn(stacked_params, micro_x) -> outputs.

    stage_fn(stage_params, x) -> y with y.shape == x.shape (homogeneous
    activation). stacked_params: pytree with leading dim S = |pipe|.
    micro_x: [M, Bm, ...] microbatches. Returns [M, Bm, ...] outputs
    (replicated over the pipe axis).

    Schedule: M + S - 1 ticks; at tick t stage 0 ingests microbatch t
    (while t < M), stage s computes on what stage s-1 produced at t-1
    (ppermute ring shift), and the last stage's outputs from ticks
    S-1 .. S-2+M are the results, in microbatch order.

    `tp_axis` (opt-in) adds tensor parallelism INSIDE every stage: the
    matrix leaves of the stage params shard their contracting dim over
    that second mesh axis, and stage_fn is called with a third argument
    `mm(x, w_loc) -> x @ w` — `blocked_matmul.tp_dense`, the
    row-parallel dense whose ring form (`tp_overlap=True`) overlaps
    the partial-product matmuls with the accumulator ppermutes. The
    stage body routes every big matmul through `mm` and otherwise
    computes exactly the replicated math (activations stay replicated
    over tp). With tp_axis=None the built fn is the pre-existing
    pipeline, unchanged.
    """
    n_stage = mesh.shape[axis]
    tp_mm = None
    if tp_axis is not None:
        tp_mm = functools.partial(blocked_matmul.tp_dense, axis=tp_axis,
                                  overlap=tp_overlap)

    def body(stacked_local, micro_x):
        # stacked_local: leading dim 1 (this device's stage)
        lead = jax.tree.leaves(stacked_local)[0].shape[0]
        if lead != 1:
            raise ValueError(
                f"stacked stage params have {lead * n_stage} stages but "
                f"the '{axis}' mesh axis has {n_stage} devices — one "
                "stage per device required")
        local_params = jax.tree.map(lambda x: x[0], stacked_local)
        me = lax.axis_index(axis)
        m = micro_x.shape[0]
        ticks = m + n_stage - 1
        # pvary: the carry is device-VARYING over the pipe axis (each
        # stage holds a different activation), so the initial zeros must
        # carry that type too or scan rejects the carry
        act0 = compat.pcast(jnp.zeros_like(micro_x[0]), axis,
                            to='varying')
        perm = [(i, (i + 1) % n_stage) for i in range(n_stage)]

        def tick(act, t):
            # activation produced LAST tick moves one stage to the right
            inbound = lax.ppermute(act, axis, perm)
            feed = micro_x[jnp.minimum(t, m - 1)]
            x_in = jnp.where(me == 0, feed, inbound)
            if tp_mm is None:
                out = stage_fn(local_params, x_in)
            else:
                out = stage_fn(local_params, x_in, tp_mm)
            return out, out

        _, outs = lax.scan(tick, act0, jnp.arange(
            ticks, dtype=jnp.int32))  # [T, Bm, ...]
        # the last stage's outputs, ticks S-1 .. S-2+M, are the results;
        # zero elsewhere + psum replicates them to every pipe device
        results = lax.dynamic_slice_in_dim(outs, n_stage - 1, m, axis=0)
        results = jnp.where(me == n_stage - 1, results,
                            jnp.zeros_like(results))
        return lax.psum(results, axis_name=axis)

    def fwd(stacked_params, micro_x):
        param_specs = jax.tree.map(
            lambda x: _stage_spec(x, axis, tp_axis), stacked_params)
        # the tp branch mixes pipe-varying activations with
        # tp-replicated ones through collectives on both axes; the
        # varying-manifest checker can't type that, so it's off there —
        # the default branch keeps the strict check it always had
        kw = {} if tp_axis is None else {"check_vma": False}
        fn = compat.shard_map(
            body, mesh=mesh,
            in_specs=(param_specs, P()),
            out_specs=P(),
            **kw,
        )
        return fn(stacked_params, micro_x)

    return fwd


def make_pipeline_train_step(stage_fn: Callable, loss_fn: Callable,
                             optimizer, mesh: Mesh, *,
                             axis: str = PIPE_AXIS,
                             tp_axis: Optional[str] = None,
                             tp_overlap: bool = True):
    """Jitted pipeline-parallel training step.

    loss_fn(outputs [M, Bm, ...], labels [M, Bm, ...]) -> scalar.
    Returns step(stacked_params, opt_state, micro_x, micro_y, step_i)
    -> (new_params, new_opt_state, loss). Gradients flow through the
    scan+ppermute pipeline by autodiff; the optimizer update runs
    sharded (each pipe device updates its own stage's slice).
    `tp_axis`/`tp_overlap` forward to make_pipeline_forward (the
    sharded-matmul opt-in; stage_fn then takes the `mm` third arg).
    """
    forward = make_pipeline_forward(stage_fn, mesh, axis=axis,
                                    tp_axis=tp_axis,
                                    tp_overlap=tp_overlap)

    @jax.jit
    def step(stacked_params, opt_state, micro_x, micro_y, step_i):
        def objective(p):
            return loss_fn(forward(p, micro_x), micro_y)

        loss, grads = jax.value_and_grad(objective)(stacked_params)
        new_params, new_opt = optimizer.update(grads, opt_state,
                                               stacked_params, step_i)
        return new_params, new_opt, loss

    return step
