"""Pipeline parallelism over a `pipe` mesh axis (GPipe-style).

The reference has NO pipeline engine (SURVEY §2.8: interleaved pipeline
absent — its model parallelism is device-pinned layers,
ParallelNeuralNetwork.cpp); this is the TPU-native extra that completes
the mesh-axis family {data, model, seq, PIPE}: S homogeneous stages'
parameters live stacked on a leading axis sharded over `pipe` (each
device holds ONE stage), microbatches stream through a lax.scan over
ticks with lax.ppermute handing activations to the next stage — the
compiler-friendly pipelining idiom (static shapes, no host control
flow). Backward is jax autodiff through the scan+ppermute program
(ppermute's transpose is the reverse permute), giving a GPipe-schedule
training step without hand-written reverse plumbing.

Constraints (standard for stacked-stage pipelining): all stages share
one structure/shape (e.g. N identical residual/transformer blocks), and
the activation shape is constant across stages.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.parallel import compat

PIPE_AXIS = "pipe"


def stack_stage_params(per_stage_params) -> dict:
    """Stack a list of S identical-structure param pytrees into one
    pytree with leading dim S (shard it P('pipe') via
    shard_stage_params)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def shard_stage_params(stacked, mesh: Mesh, axis: str = PIPE_AXIS):
    """Place the stacked stage params so each pipe device holds its own
    stage's slice."""
    return jax.tree.map(
        lambda x: jax.device_put(
            x, NamedSharding(mesh, P(axis, *([None] * (x.ndim - 1))))),
        stacked)


def make_pipeline_forward(stage_fn: Callable, mesh: Mesh, *,
                          axis: str = PIPE_AXIS):
    """Build fn(stacked_params, micro_x) -> outputs.

    stage_fn(stage_params, x) -> y with y.shape == x.shape (homogeneous
    activation). stacked_params: pytree with leading dim S = |pipe|.
    micro_x: [M, Bm, ...] microbatches. Returns [M, Bm, ...] outputs
    (replicated over the pipe axis).

    Schedule: M + S - 1 ticks; at tick t stage 0 ingests microbatch t
    (while t < M), stage s computes on what stage s-1 produced at t-1
    (ppermute ring shift), and the last stage's outputs from ticks
    S-1 .. S-2+M are the results, in microbatch order.
    """
    n_stage = mesh.shape[axis]

    def body(stacked_local, micro_x):
        # stacked_local: leading dim 1 (this device's stage)
        lead = jax.tree.leaves(stacked_local)[0].shape[0]
        if lead != 1:
            raise ValueError(
                f"stacked stage params have {lead * n_stage} stages but "
                f"the '{axis}' mesh axis has {n_stage} devices — one "
                "stage per device required")
        local_params = jax.tree.map(lambda x: x[0], stacked_local)
        me = lax.axis_index(axis)
        m = micro_x.shape[0]
        ticks = m + n_stage - 1
        # pvary: the carry is device-VARYING over the pipe axis (each
        # stage holds a different activation), so the initial zeros must
        # carry that type too or scan rejects the carry
        act0 = compat.pcast(jnp.zeros_like(micro_x[0]), axis,
                            to='varying')
        perm = [(i, (i + 1) % n_stage) for i in range(n_stage)]

        def tick(act, t):
            # activation produced LAST tick moves one stage to the right
            inbound = lax.ppermute(act, axis, perm)
            feed = micro_x[jnp.minimum(t, m - 1)]
            x_in = jnp.where(me == 0, feed, inbound)
            out = stage_fn(local_params, x_in)
            return out, out

        _, outs = lax.scan(tick, act0, jnp.arange(
            ticks, dtype=jnp.int32))  # [T, Bm, ...]
        # the last stage's outputs, ticks S-1 .. S-2+M, are the results;
        # zero elsewhere + psum replicates them to every pipe device
        results = lax.dynamic_slice_in_dim(outs, n_stage - 1, m, axis=0)
        results = jnp.where(me == n_stage - 1, results,
                            jnp.zeros_like(results))
        return lax.psum(results, axis_name=axis)

    def fwd(stacked_params, micro_x):
        fn = compat.shard_map(
            body, mesh=mesh,
            in_specs=(jax.tree.map(lambda _: P(axis), stacked_params),
                      P()),
            out_specs=P(),
        )
        return fn(stacked_params, micro_x)

    return fwd


def make_pipeline_train_step(stage_fn: Callable, loss_fn: Callable,
                             optimizer, mesh: Mesh, *,
                             axis: str = PIPE_AXIS):
    """Jitted pipeline-parallel training step.

    loss_fn(outputs [M, Bm, ...], labels [M, Bm, ...]) -> scalar.
    Returns step(stacked_params, opt_state, micro_x, micro_y, step_i)
    -> (new_params, new_opt_state, loss). Gradients flow through the
    scan+ppermute pipeline by autodiff; the optimizer update runs
    sharded (each pipe device updates its own stage's slice).
    """
    forward = make_pipeline_forward(stage_fn, mesh, axis=axis)

    @jax.jit
    def step(stacked_params, opt_state, micro_x, micro_y, step_i):
        def objective(p):
            return loss_fn(forward(p, micro_x), micro_y)

        loss, grads = jax.value_and_grad(objective)(stacked_params)
        new_params, new_opt = optimizer.update(grads, opt_state,
                                               stacked_params, step_i)
        return new_params, new_opt, loss

    return step
