"""Train a small transformer LM and sample from it — the modern
flagship's user flow (dense or MoE, long-context ready).

Run: python examples/transformer_lm.py [--steps 200] [--moe]

The task is character-level copy-structure text (synthetic, zero
egress): sequences follow an order-1 Markov chain, so a small model
learns it quickly and greedy samples show the learned structure.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np

from paddle_tpu import optim
from paddle_tpu.models import transformer as T


def make_batch(rng, vocab, batch, seq_len):
    toks = np.empty((batch, seq_len), np.int32)
    toks[:, 0] = rng.randint(0, vocab, batch)
    for t in range(1, seq_len):
        toks[:, t] = (3 * toks[:, t - 1] + rng.randint(0, 5, batch)) % vocab
    return jnp.asarray(toks)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--seq-len", type=int, default=64)
    ap.add_argument("--vocab", type=int, default=128)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--moe", action="store_true",
                    help="sparse FFN blocks (4 experts, top-2)")
    args = ap.parse_args()

    cfg = T.TransformerConfig(
        vocab=args.vocab, dim=args.dim, n_layers=args.layers, n_heads=4,
        attn_impl="auto",
        moe_experts=4 if args.moe else 0, moe_capacity_factor=2.0)
    params = T.init_params(jax.random.key(0), cfg)
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(params))
    print(f"{'MoE' if args.moe else 'dense'} transformer: "
          f"{n_params:,} parameters")

    opt = optim.adam(3e-3)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, toks, i):
        loss, grads = jax.value_and_grad(
            lambda p: T.loss(p, cfg, toks))(params)
        params, opt_state = opt.update(grads, opt_state, params, i)
        return params, opt_state, loss

    r = np.random.RandomState(0)
    for i in range(args.steps):
        toks = make_batch(r, args.vocab, args.batch, args.seq_len)
        params, opt_state, loss = step(params, opt_state, toks,
                                       jnp.asarray(i))
        if i % 50 == 0 or i == args.steps - 1:
            print(f"step {i:4d}  loss {float(loss):.4f}")

    prompt = make_batch(np.random.RandomState(7), args.vocab, 2, 8)
    out = T.generate(params, cfg, prompt, steps=12)
    print("greedy samples (prompt | continuation):")
    for row in np.asarray(out):
        print(" ", [int(v) for v in row[:8]], "|",
              [int(v) for v in row[8:]])
    # the learned rule is next = (3*tok + U[0,5)) % vocab — check the
    # first continuation step obeys it for both samples
    ok = all((row[8] - 3 * row[7]) % args.vocab < 5 for row in np.asarray(out))
    print("continuations obey the chain rule:", ok)

    # --- serving: the whole decode loop as one int8 artifact ---------
    # (the reference served generation from a live SequenceGenerator;
    # here prefill + scan + weights compile into a single file any
    # jax-only process can run — no model code, quantized weights)
    import tempfile

    from paddle_tpu.serve import export_decoder, load_compiled_model

    path = os.path.join(tempfile.mkdtemp(), "lm_decoder.ptc")
    export_decoder(params, cfg, path, batch=2, prompt_len=8, steps=12,
                   int8_weights=True)
    served = load_compiled_model(path)
    served_out = np.asarray(served.predict(np.asarray(prompt)))
    # agreement over the CONTINUATIONS only (the prompt echo is free)
    match = (served_out[:, 8:] == np.asarray(out)[:, 8:]).mean()
    print(f"served int8 decoder: {os.path.getsize(path)/1e3:.0f} kB "
          f"artifact, {match:.0%} continuation agreement with the "
          "full-precision in-process decode")


if __name__ == "__main__":
    main()
