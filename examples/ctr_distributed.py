"""CTR wide&deep with sharded embeddings on a device mesh — the
reference's sparse-remote training (row-sharded tables, only touched
rows move; reference: pserver getParameterSparse, SparseRowMatrix)
as mesh embedding-parallelism with owner-routed all-to-all.

Runs on whatever devices exist; to simulate a multi-chip mesh on CPU:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/ctr_distributed.py
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np

from paddle_tpu import optim
from paddle_tpu.core import mesh as mesh_lib
from paddle_tpu.models.ctr import CTRModel


def run_pserver_demo(args):
    """The pserver-tier variant of the sparse tail: the table lives in
    host RAM on replicated `native.pserver` shards (leases, exactly-once
    push epochs, chain replication), and the trainer looks up / pushes
    through `PServerEmbedding` — the same call surface as
    ShardedEmbedding. Midway, the primary of shard 0 is KILLED to show
    the failover: training finishes through the replica with no lost or
    duplicated row updates (docs/RELIABILITY.md "Parameter-server fault
    model")."""
    from paddle_tpu.native.pserver import PServerGroup
    from paddle_tpu.parallel.pserver_client import (PServerClient,
                                                    PServerEmbedding)

    vocab = (args.vocab // 4) * 4
    with PServerGroup(vocab, args.dim, n_shards=4) as group:
        with PServerClient(group.specs, args.dim, trainer_id=0) as client:
            client.register()
            emb = PServerEmbedding(client)
            table = emb.init(jax.random.key(0))
            rs = np.random.RandomState(0)
            w = np.zeros(args.dim, np.float32)
            for i in range(args.steps):
                ids = rs.randint(0, vocab, args.batch).astype(np.int64)
                labels = (ids < vocab // 5).astype(np.float32)
                vecs = np.asarray(emb.lookup(table, ids))
                logits = vecs @ w
                p = 1.0 / (1.0 + np.exp(-logits))
                g = (p - labels)[:, None]
                w -= 0.05 * (g * vecs).mean(0)
                emb.apply_row_grads(table, ids, g * w[None, :] / len(ids),
                                    lr=0.05)
                if i == args.steps // 2:
                    group.primaries[0].kill()
                    print(f"step {i}: killed shard 0 primary — failing "
                          f"over to its replica")
                if i % 10 == 0:
                    loss = float(np.mean(
                        -labels * np.log(p + 1e-7)
                        - (1 - labels) * np.log(1 - p + 1e-7)))
                    print(f"step {i} logloss {loss:.4f}")
            client.finish_pass()
            print(f"pass finished through the failover; client stats "
                  f"{client.stats}")


def run_online_demo(args):
    """The full production loop in one process: a `TaskQueue` streams
    training tasks into a `StreamingTrainer` (no pass barrier — tasks
    flow continuously, pushes numbered by the exactly-once epoch
    watermark), the pushed rows land on `native.pserver` shards, and a
    `TieredEmbedCache` + `CtrServer` serve scores concurrently — the
    cache hears every push ACK through `bind_push_feed` and never
    serves a row staler than `max_staleness` pushes
    (docs/SERVING.md "Tiered embedding serving")."""
    import json

    from paddle_tpu.native.pserver import PServerGroup
    from paddle_tpu.native.taskqueue import TaskQueue
    from paddle_tpu.parallel.pserver_client import (PServerClient,
                                                    PServerEmbedding)
    from paddle_tpu.serve.ctr import CtrServer, init_tower
    from paddle_tpu.serve.embed_cache import TieredEmbedCache
    from paddle_tpu.train.online import StreamingTrainer

    vocab = (args.vocab // 4) * 4
    with PServerGroup(vocab, args.dim, n_shards=4) as group:
        push = PServerClient(group.specs, args.dim, trainer_id=0)
        push.register()
        emb = PServerEmbedding(push)
        table = emb.init(jax.random.key(0))

        queue = TaskQueue(timeout_ms=2000, max_retries=3)
        for i in range(args.steps):
            queue.add_task(json.dumps(
                {"seed": i, "batch": 8, "slots": 4,
                 "vocab": vocab}).encode())
        trainer = StreamingTrainer(queue, emb, table, lr=0.05)

        read = PServerClient(group.specs, args.dim, trainer_id=1)
        read.register()
        cache = TieredEmbedCache(PServerEmbedding(read), table,
                                 hot_rows=1024, host_rows=4096,
                                 max_staleness=4)
        cache.bind_push_feed(push)
        server = CtrServer(cache, init_tower(jax.random.key(1),
                                             args.dim),
                           slots=args.slots, max_batch=8)

        rs = np.random.RandomState(7)
        served = 0
        while trainer.stats["tasks_done"] < args.steps:
            trainer.step()               # streams: no pass barrier
            ids = rs.randint(0, vocab, (4, args.slots))
            scores = server.score(ids.astype(np.int64))
            served += len(scores)
            cache.refresh_stale()        # maintenance tick, off path
            if trainer.stats["tasks_done"] % 10 == 0:
                c = cache.counters()
                print(f"streamed {trainer.stats['tasks_done']:3d} "
                      f"tasks | served {served:4d} scores | cache "
                      f"hits {c['hits_device']} misses {c['misses']} "
                      f"stale-refills {c['stale_refills']}")
        rec = cache.reconcile([p.stats() for p in group.primaries])
        print(f"stream drained: trainer {trainer.stats} | "
              f"reconcile ok={rec['ok']} watermarks_match="
              f"{rec.get('watermarks_match_push_ledger')}")
        push.close()
        read.close()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--slots", type=int, default=16)
    ap.add_argument("--vocab", type=int, default=100_000)
    ap.add_argument("--dim", type=int, default=32)
    ap.add_argument("--pserver", action="store_true",
                    help="train the sparse tail against a local "
                         "fault-tolerant parameter-server tier (and "
                         "kill a primary midway to show failover)")
    ap.add_argument("--online", action="store_true",
                    help="stream tasks through a StreamingTrainer into "
                         "the pserver tier while a TieredEmbedCache + "
                         "CtrServer serve scores concurrently — the "
                         "production online-learning loop")
    args = ap.parse_args()

    if args.online:
        run_online_demo(args)
        return
    if args.pserver:
        run_pserver_demo(args)
        return

    n_dev = len(jax.devices())
    mesh = mesh_lib.build_mesh(mesh_lib.MeshConfig(data=1, model=n_dev))
    print(f"mesh: {n_dev} device(s) on the '{mesh_lib.MODEL_AXIS}' axis; "
          f"tables row-sharded, lookups owner-routed all-to-all")

    model = CTRModel(vocab=args.vocab, embed_dim=args.dim, mesh=mesh)
    params, mlp_state = model.init(jax.random.key(0), args.batch, args.slots)
    opt = optim.adam(1e-3)
    opt_state = opt.init(params["mlp"])
    step = model.make_train_step(opt, mlp_state)

    rs = np.random.RandomState(0)
    lr = jnp.asarray(0.05, jnp.float32)
    for i in range(args.steps):
        ids = rs.randint(0, args.vocab, (args.batch, args.slots))
        # clicks correlate with low feature ids (a learnable signal)
        labels = (ids.min(1) < args.vocab // 5).astype(np.float32)
        params, opt_state, loss = step(
            params, opt_state, jnp.asarray(ids, jnp.int32),
            jnp.asarray(labels), lr, jnp.asarray(i, jnp.int32),
            jax.random.key(i))
        if i % 10 == 0:
            print(f"step {i} logloss {float(loss):.4f}")
    print(f"final logloss {float(loss):.4f}")


if __name__ == "__main__":
    main()
