"""CTR wide&deep with sharded embeddings on a device mesh — the
reference's sparse-remote training (row-sharded tables, only touched
rows move; reference: pserver getParameterSparse, SparseRowMatrix)
as mesh embedding-parallelism with owner-routed all-to-all.

Runs on whatever devices exist; to simulate a multi-chip mesh on CPU:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/ctr_distributed.py
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np

from paddle_tpu import optim
from paddle_tpu.core import mesh as mesh_lib
from paddle_tpu.models.ctr import CTRModel


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--slots", type=int, default=16)
    ap.add_argument("--vocab", type=int, default=100_000)
    ap.add_argument("--dim", type=int, default=32)
    args = ap.parse_args()

    n_dev = len(jax.devices())
    mesh = mesh_lib.build_mesh(mesh_lib.MeshConfig(data=1, model=n_dev))
    print(f"mesh: {n_dev} device(s) on the '{mesh_lib.MODEL_AXIS}' axis; "
          f"tables row-sharded, lookups owner-routed all-to-all")

    model = CTRModel(vocab=args.vocab, embed_dim=args.dim, mesh=mesh)
    params, mlp_state = model.init(jax.random.key(0), args.batch, args.slots)
    opt = optim.adam(1e-3)
    opt_state = opt.init(params["mlp"])
    step = model.make_train_step(opt, mlp_state)

    rs = np.random.RandomState(0)
    lr = jnp.asarray(0.05, jnp.float32)
    for i in range(args.steps):
        ids = rs.randint(0, args.vocab, (args.batch, args.slots))
        # clicks correlate with low feature ids (a learnable signal)
        labels = (ids.min(1) < args.vocab // 5).astype(np.float32)
        params, opt_state, loss = step(
            params, opt_state, jnp.asarray(ids, jnp.int32),
            jnp.asarray(labels), lr, jnp.asarray(i, jnp.int32),
            jax.random.key(i))
        if i % 10 == 0:
            print(f"step {i} logloss {float(loss):.4f}")
    print(f"final logloss {float(loss):.4f}")


if __name__ == "__main__":
    main()
