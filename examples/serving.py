"""The serving user flow, end to end: train a small LM, quantize it,
and serve streaming traffic through the continuous-batching engine.

Run: python examples/serving.py [--steps 120] [--no-quant]

Covers, in order:
  1. train      — transformer LM on synthetic Markov text (zero egress)
  2. quantize   — weight-only int8 (serve.quantize_params) + int8 KV
                  cache (TransformerConfig.kv_cache_dtype)
  3. serve      — DecodeEngine slot pool: mixed-length prompts, bucket
                  padding, eos retirement, admit-on-free
  4. check      — every greedy request token-matches its solo
                  generate() run (the engine's consistency contract)

The reference's closest surface is the lockstep SequenceGenerator
(reference: api/PaddleAPI.h:1025); steps 2-3 are the beyond-reference
serving stack.
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np

from paddle_tpu import optim
from paddle_tpu.models import transformer as T
from paddle_tpu.serve import DecodeEngine, quantize_params

VOCAB, EOS = 64, 63


def make_batch(rng, batch, seq_len):
    """Order-1 Markov chains: token t+1 = (3*t + noise) % (VOCAB-1),
    easily learned, never emitting the reserved EOS id."""
    toks = np.zeros((batch, seq_len), np.int32)
    toks[:, 0] = rng.randint(0, VOCAB - 1, batch)
    for j in range(1, seq_len):
        noise = rng.randint(0, 3, batch)
        toks[:, j] = (3 * toks[:, j - 1] + noise) % (VOCAB - 1)
    return jnp.asarray(toks)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=120)
    ap.add_argument("--no-quant", action="store_true")
    args = ap.parse_args()

    cfg = T.TransformerConfig(vocab=VOCAB, dim=64, n_layers=2,
                              n_heads=4, attn_impl="dense")
    params = T.init_params(jax.random.key(0), cfg)
    opt = optim.adam(3e-3)
    opt_state = opt.init(params)
    rng = np.random.RandomState(0)

    @jax.jit
    def step(p, s, toks, i):
        loss, grads = jax.value_and_grad(
            lambda p: T.loss(p, cfg, toks))(p)
        p, s = opt.update(grads, s, p, i)
        return p, s, loss

    print(f"[1/4] training {args.steps} steps ...")
    for i in range(args.steps):
        params, opt_state, loss = step(params, opt_state,
                                       make_batch(rng, 16, 33),
                                       jnp.int32(i))
        if i % 40 == 0:
            print(f"   step {i:4d}  loss {float(loss):.3f}")
    print(f"   final loss {float(loss):.3f}")

    serve_cfg = cfg
    if not args.no_quant:
        print("[2/4] quantizing: int8 weights + int8 KV cache")
        params = quantize_params(params)
        serve_cfg = dataclasses.replace(cfg, kv_cache_dtype="int8")
    else:
        print("[2/4] quantization skipped (--no-quant)")

    print("[3/4] serving 9 mixed-length requests through 3 slots")
    prompts = [np.asarray(make_batch(rng, 1, l))[0]
               for l in (5, 9, 13, 6, 11, 5, 8, 14, 7)]
    eng = DecodeEngine(params, serve_cfg, slots=3, max_len=48,
                       eos_id=EOS)
    # greedy requests (the consistency check below relies on them)
    # beside two seeded sampled ones — per-request sampling shares the
    # same compiled step, and the seeds make those two reproducible
    # regardless of pool co-tenancy
    sampling = [{}] * 9
    sampling[2] = {"temperature": 0.9, "top_p": 0.95, "seed": 7}
    sampling[6] = {"temperature": 0.7, "top_k": 12, "seed": 8}
    outs = eng.serve(prompts, max_new=12, buckets=(8, 16),
                     sampling=sampling)
    for i, (p, o) in enumerate(zip(prompts, outs)):
        print(f"   req{i} (len {len(p):2d}): +{len(o)} tokens "
              f"{o[:6]}{'...' if len(o) > 6 else ''}")

    print("[4/4] consistency check vs solo generate() (greedy rows)")
    for i, (p, o) in enumerate(zip(prompts, outs)):
        if i in (2, 6):      # the sampled requests follow their own
            continue         # seeded streams, not the greedy path
        ref = T.generate(params, serve_cfg, jnp.asarray(p)[None, :],
                         steps=12, eos_id=EOS)
        ref = [int(t) for t in np.asarray(ref[0, len(p):])]
        if EOS in ref:
            ref = ref[:ref.index(EOS) + 1]
        assert o == ref, (p, o, ref)
    print("   all requests token-equal to their solo decode. done.")


if __name__ == "__main__":
    main()
