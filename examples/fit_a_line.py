"""Linear regression on UCI housing — the reference's first book chapter
(reference: python/paddle/v2/fluid/tests/book/test_fit_a_line.py: one fc
to a single output, squared-error cost, SGD) on the TPU-native stack.

Run: python examples/fit_a_line.py [--passes 20] [--batch 32]
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp

from paddle_tpu import data, nn, optim
from paddle_tpu.data import datasets
from paddle_tpu.nn.module import ShapeSpec
from paddle_tpu.ops import losses
from paddle_tpu.train import Trainer, events as E


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--passes", type=int, default=20)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--lr", type=float, default=1e-2)
    args = ap.parse_args()

    model = nn.Dense(1, name="predict")
    trainer = Trainer(
        model,
        loss_fn=lambda pred, y: jnp.mean(
            losses.squared_error(pred[:, 0], y)),
        optimizer=optim.sgd(args.lr),
    )
    state = trainer.init_state(ShapeSpec((args.batch, 13)))

    feeder = data.DataFeeder()

    def batches():
        return feeder(data.batch_reader(
            data.reader.shuffle(datasets.uci_housing("train"), 512, seed=0),
            args.batch))

    def handler(ev):
        if isinstance(ev, E.EndIteration) and ev.batch_id == 0:
            print(f"pass {ev.pass_id} cost {float(ev.cost):.4f}")

    state = trainer.train(state, batches, num_passes=args.passes,
                          event_handler=handler)

    x, y = next(iter(batches()))
    pred, _ = model.apply(state.params, state.model_state, x,
                          training=False)
    print("sample predictions vs labels:")
    for i in range(5):
        print(f"  pred {float(pred[i, 0]):8.2f}   label {float(y[i]):8.2f}")


if __name__ == "__main__":
    main()
