"""Seq2seq NMT with attention + beam-search generation — the
capability the reference exercises through recurrent_group +
simple_attention + generation (reference:
trainer/tests/sample_trainer_rnn_gen.conf, networks.py simple_attention).

Trains on a synthetic copy/reverse task (zero-egress stand-in for WMT)
and decodes with beam search.

Run: python examples/seq2seq_nmt.py [--steps 300] [--beam 4]
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np

from paddle_tpu import optim
from paddle_tpu.models import seq2seq_attn

BOS, EOS = 0, 1


def make_batch(rs, batch, max_len, vocab):
    """Task: target = reversed source (forces real attention use)."""
    lens = rs.randint(3, max_len + 1, batch)
    src = np.full((batch, max_len), EOS, np.int32)
    tgt = np.full((batch, max_len + 1), EOS, np.int32)
    for i, n in enumerate(lens):
        toks = rs.randint(2, vocab, n)
        src[i, :n] = toks
        tgt[i, 0] = BOS
        tgt[i, 1:n + 1] = toks[::-1]
    return (jnp.asarray(src), jnp.asarray(lens),
            jnp.asarray(tgt), jnp.asarray(lens + 1))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=600)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--max-len", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=30)
    ap.add_argument("--beam", type=int, default=4)
    args = ap.parse_args()

    rs = np.random.RandomState(0)
    params = seq2seq_attn.init_params(
        jax.random.key(0), args.vocab, args.vocab, embed_dim=32, hidden=64)
    opt = optim.adam(2e-3)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, src, src_lens, tgt, tgt_lens):
        loss, grads = jax.value_and_grad(
            lambda p: seq2seq_attn.loss(p, src, src_lens, tgt, tgt_lens)
        )(params)
        new_p, new_o = opt.update(grads, opt_state, params,
                                  jnp.zeros((), jnp.int32))
        return new_p, new_o, loss

    for i in range(args.steps):
        batch = make_batch(rs, args.batch, args.max_len, args.vocab)
        params, opt_state, loss = step(params, opt_state, *batch)
        if i % 50 == 0:
            print(f"step {i} loss {float(loss):.4f}")

    # beam-search decode a few held-out sources
    src, src_lens, tgt, _ = make_batch(rs, 4, args.max_len, args.vocab)
    out, scores, out_lens = seq2seq_attn.generate(
        params, src, src_lens, beam_size=args.beam,
        max_len=args.max_len + 1, bos_id=BOS, eos_id=EOS)
    ok = 0
    for i in range(4):
        n = int(src_lens[i])
        want = [int(t) for t in np.asarray(src[i, :n])[::-1]]
        best = np.asarray(out[i, 0]).tolist()  # top beam hypothesis
        got = [t for t in best if t >= 2][:n]
        ok += got == want
        print(f"src {np.asarray(src[i, :n]).tolist()} -> decoded {got} "
              f"(want {want})")
    print(f"exact reversals: {ok}/4")


if __name__ == "__main__":
    main()
