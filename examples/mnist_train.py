"""MNIST LeNet training — the reference's v1_api_demo/mnist/api_train.py
rebuilt on the TPU-native stack.

Run: python examples/mnist_train.py [--passes 3] [--batch 64]

Uses real MNIST idx files when PADDLE_TPU_DATA_HOME provides them, the
synthetic surrogate otherwise (zero-egress environments; see README
"Real datasets").
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp

from paddle_tpu import data, models, optim
from paddle_tpu.data import datasets
from paddle_tpu.nn.module import ShapeSpec
from paddle_tpu.ops import losses, metrics
from paddle_tpu.train import Trainer, events as E


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--passes", type=int, default=3)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--lr", type=float, default=1e-3)
    args = ap.parse_args()

    model = models.lenet.lenet(num_classes=10)
    trainer = Trainer(
        model,
        loss_fn=lambda logits, labels: jnp.mean(
            losses.softmax_cross_entropy(logits, labels)),
        optimizer=optim.adam(args.lr),
        metrics_fn=lambda logits, labels: {
            "acc": metrics.accuracy(logits, labels)},
    )
    state = trainer.init_state(ShapeSpec((args.batch, 28, 28, 1)))

    feeder = data.DataFeeder()

    def batches():
        return feeder(data.batch_reader(
            data.reader.shuffle(datasets.mnist("train"), 4096, seed=0), args.batch))

    def handler(ev):
        if isinstance(ev, E.EndIteration) and ev.batch_id % 100 == 0:
            print(f"pass {ev.pass_id} batch {ev.batch_id} "
                  f"cost {float(ev.cost):.4f}")
        if isinstance(ev, E.EndPass):
            print(f"== pass {ev.pass_id} done")

    state = trainer.train(state, batches, num_passes=args.passes,
                          event_handler=handler)

    # held-out evaluation
    test = feeder(data.batch_reader(datasets.mnist("test"), args.batch))
    res = trainer.evaluate(state, lambda: test)
    print(f"test: cost {float(res.cost):.4f} "
          + " ".join(f"{k} {float(v):.4f}" for k, v in res.metrics.items()))


if __name__ == "__main__":
    main()
