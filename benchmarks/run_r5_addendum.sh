#!/bin/bash
# Round-5 addendum: stages added AFTER the main campaign fired (it
# launched minutes into the round, the moment the chip answered).
# Waits for the main campaign to release the chip claim, then runs the
# new rows under the same wedge discipline (r5_common.sh probe +
# STOP_EPOCH cap).
set -u
cd "$(dirname "$0")/.."
. benchmarks/r5_common.sh
mkdir -p benchmarks/r5_logs

# wait for the main campaign to finish (its console gains "=== done")
while ! grep -q "=== done" benchmarks/r5_logs/campaign_console.txt 2>/dev/null; do
  if [ "$(date +%s)" -ge "$STOP_EPOCH" ]; then
    echo "=== main campaign still running at STOP_EPOCH — addendum aborted ==="
    exit 0
  fi
  sleep 60
done

wait_alive() {
  while true; do
    if [ "$(date +%s)" -ge "$STOP_EPOCH" ]; then
      echo "=== chip still wedged at STOP_EPOCH — aborting addendum ==="
      exit 0
    fi
    if chip_probe >> benchmarks/r5_logs/realive.log 2>&1; then
      echo "    (chip alive again $(date +%H:%M:%S))"
      return
    fi
    echo "    (chip not answering, re-probe in 300s)"
    sleep 300
  done
}

run() {  # name timeout cmd...  (same contract as run_r5_measurements.sh)
  local name=$1 tmo=$2; shift 2
  local now=$(date +%s)
  if [ "$now" -ge "$STOP_EPOCH" ]; then
    echo "=== $name SKIPPED (past STOP_EPOCH) ==="
    return
  fi
  local budget=$(( STOP_EPOCH - now ))
  if [ "$tmo" -gt "$budget" ]; then tmo=$budget; fi
  echo "=== $name ($(date +%H:%M:%S), budget ${tmo}s) ==="
  timeout "$tmo" "$@" > "benchmarks/r5_logs/$name.out" 2> "benchmarks/r5_logs/$name.err"
  local rc=$?
  echo "    rc=$rc  (tail of out:)"; tail -3 "benchmarks/r5_logs/$name.out" | sed 's/^/    /'
  if [ "$rc" = 124 ]; then
    wait_alive
  fi
}

echo "=== addendum probe ($(date +%H:%M:%S)) ==="
chip_probe > benchmarks/r5_logs/add_probe.out 2> benchmarks/r5_logs/add_probe.err \
  || wait_alive

# fused chunked cross-entropy A/B vs the transformer row suite_misc
# measured (same shape; the delta is the 4.19 GiB logits round-trip)
run suite_fused_ce 2400 python benchmarks/suite.py --only transformer_fused_ce

echo "=== addendum done ($(date +%H:%M:%S)) ==="
