#!/bin/bash
# Round-5 tail-2: rows for features built after the tail was armed —
# the continuous-batching serving engine A/B. Chains behind
# run_r5_tail.sh; same wedge discipline.
set -u
cd "$(dirname "$0")/.."
. benchmarks/r5_common.sh
mkdir -p benchmarks/r5_logs

while ! grep -q "tail done\|aborting tail\|tail aborted" \
        benchmarks/r5_logs/tail_console.txt 2>/dev/null; do
  if [ "$(date +%s)" -ge "$STOP_EPOCH" ]; then
    echo "=== tail still waiting at STOP_EPOCH — tail2 aborted ==="
    exit 0
  fi
  sleep 60
done

run() {  # name timeout cmd...
  local name=$1 tmo=$2; shift 2
  local now=$(date +%s)
  if [ "$now" -ge "$STOP_EPOCH" ]; then
    echo "=== $name SKIPPED (past STOP_EPOCH) ==="
    return
  fi
  local budget=$(( STOP_EPOCH - now ))
  if [ "$tmo" -gt "$budget" ]; then tmo=$budget; fi
  echo "=== $name ($(date +%H:%M:%S), budget ${tmo}s) ==="
  timeout "$tmo" "$@" > "benchmarks/r5_logs/$name.out" 2> "benchmarks/r5_logs/$name.err"
  local rc=$?
  echo "    rc=$rc  (tail of out:)"; tail -3 "benchmarks/r5_logs/$name.out" | sed 's/^/    /'
}

echo "=== tail2 probe ($(date +%H:%M:%S)) ==="
chip_probe > benchmarks/r5_logs/tail2_probe.out 2> benchmarks/r5_logs/tail2_probe.err \
  || { echo "chip not answering — tail2 aborted"; exit 0; }

# continuous-batching engine vs lockstep baseline (serving throughput)
run suite_engine 2400 python benchmarks/suite.py --only engine

echo "=== tail2 done ($(date +%H:%M:%S)) ==="
