# Shared definitions for the r5 watcher + campaign (sourced, not run).
#
# STOP_EPOCH: unix time after which no chip work may start (and running
# stages are capped) so the driver's end-of-round bench owns the claim.
# Round 5 started 2026-08-01 ~08:26 UTC with a ~12h window; stand down
# ~1.4h before the expected end.
export STOP_EPOCH=${STOP_EPOCH:-1785611000}   # 2026-08-01 19:03 UTC

# One liveness criterion everywhere (same as r4_common.sh): the tiny
# matmul must complete AND the backend must be the chip (platform
# "axon" through the relay; a silent CPU fallback would otherwise
# declare a wedged chip alive and launch the next heavy stage into it).
#
# 600s probe budget: the r3+r4 wedge persisted 16+ hours under a
# 150s/5-min prober — consistent with each killed probe grabbing the
# claim the moment the previous wedge expires and being SIGTERMed
# mid-init, re-wedging the relay for another window. A probe long
# enough to ride out a slow grant (+ the ~30s compile) breaks that
# cycle instead of perpetuating it.
chip_probe() {
  timeout 600 python -c "
import jax, jax.numpy as jnp
assert jax.default_backend() != 'cpu', jax.default_backend()
print((jnp.ones((128,128),jnp.bfloat16)@jnp.ones((128,128),jnp.bfloat16))[0,0])
"
}
