#!/bin/bash
# Round-4 watcher: probe the chip every PERIOD seconds; the moment the
# probe completes, fire the r4 measurement campaign once and exit.
# Each probe runs in its own subprocess under `timeout` — a wedged relay
# makes the probe hang, the timeout reaps it, we sleep and retry.
#
# r4 changes vs watch_and_measure.sh: the deadline is unix-epoch based
# (HHMM comparison broke across midnight, and round 4 spans it), and the
# probe + STOP_EPOCH live in r4_common.sh shared with the campaign so
# the two can't desynchronize.
set -u
cd "$(dirname "$0")/.."
. benchmarks/r4_common.sh
PERIOD=${PERIOD:-600}
LOG=benchmarks/r4_logs/watcher.log
mkdir -p benchmarks/r4_logs

while true; do
  if [ "$(date +%s)" -ge "$STOP_EPOCH" ]; then
    echo "[watcher $(date +%H:%M:%S)] past STOP_EPOCH — standing down so the driver's bench owns the chip" | tee -a "$LOG"
    exit 0
  fi
  if chip_probe >> "$LOG" 2>&1; then
    echo "[watcher $(date +%H:%M:%S)] chip ANSWERED — firing campaign" | tee -a "$LOG"
    bash benchmarks/run_r4_measurements.sh 2>&1 | tee -a benchmarks/r4_logs/campaign_console.txt
    exit 0
  fi
  echo "[watcher $(date +%H:%M:%S)] chip still wedged; retry in ${PERIOD}s" >> "$LOG"
  sleep "$PERIOD"
done
