#!/bin/bash
# Round-5 chip measurement campaign — cashes the two-round unmeasured
# IOU table (VERDICT r4 #1): every committed-but-unmeasured suite row
# gets a number, cheapest/highest-information first.
#
# Inherits the r3b/r4 wedge lessons (run_r3_measurements.sh header):
# cheap compiles first, A/B probes early, subprocess-isolated stages,
# big-batch image rows last, STOP_EPOCH cap so a late stage never
# collides with the driver's own end-of-round bench.
set -u
cd "$(dirname "$0")/.."
. benchmarks/r5_common.sh   # STOP_EPOCH + chip_probe (shared w/ watcher)
mkdir -p benchmarks/r5_logs

# a stage killed at its timeout may have wedged the relay (the r3
# hazard: a killed claimant wedges the chip ~2h) — launching the next
# stage into a wedged chip just burns its full timeout and re-wedges.
# After any rc=124, hold here re-probing until the chip answers again
# (or STOP_EPOCH passes, which aborts the campaign).
wait_alive() {
  while true; do
    if [ "$(date +%s)" -ge "$STOP_EPOCH" ]; then
      echo "=== chip still wedged at STOP_EPOCH — aborting campaign ==="
      exit 0
    fi
    if chip_probe >> benchmarks/r5_logs/realive.log 2>&1; then
      echo "    (chip alive again $(date +%H:%M:%S))"
      return
    fi
    echo "    (chip not answering, re-probe in 300s)"
    sleep 300
  done
}

run() {  # name timeout cmd...
  local name=$1 tmo=$2; shift 2
  local now=$(date +%s)
  if [ "$now" -ge "$STOP_EPOCH" ]; then
    echo "=== $name SKIPPED (past STOP_EPOCH) ==="
    return
  fi
  # cap the stage budget at the deadline: a stage launched shortly
  # before STOP_EPOCH must not run its full timeout past it and
  # collide with the driver's own bench on the single chip claim
  local budget=$(( STOP_EPOCH - now ))
  if [ "$tmo" -gt "$budget" ]; then tmo=$budget; fi
  echo "=== $name ($(date +%H:%M:%S), budget ${tmo}s) ==="
  timeout "$tmo" "$@" > "benchmarks/r5_logs/$name.out" 2> "benchmarks/r5_logs/$name.err"
  local rc=$?
  echo "    rc=$rc  (tail of out:)"; tail -3 "benchmarks/r5_logs/$name.out" | sed 's/^/    /'
  if [ "$rc" = 124 ]; then
    wait_alive
  fi
}

# 0. liveness (same criterion as wait_alive)
echo "=== probe ($(date +%H:%M:%S)) ==="
chip_probe > benchmarks/r5_logs/probe.out 2> benchmarks/r5_logs/probe.err \
  || wait_alive

# 1. the open regression question (two rounds old): tie-split vs
#    select-and-scatter maxpool backward, resnet bs64
run probe_pool 1500 python benchmarks/probe_pool.py

# 1b. the HBM-roofline attack at its cheapest shape: remat A/B at bs64
#     (full bs-256 rows run in stage 6; this early row survives even if
#     a later compile wedges the chip)
run probe_remat 2400 python benchmarks/suite.py --only resnet50,resnet50_remat,resnet50_remat_full --batches 64

# 2. lstm benches (fused Pallas kernel) + the h256/h512 inversion probe
run suite_lstm 1200 python benchmarks/suite.py --only lstm_h256,lstm_h512
run probe_lstm 1200 python benchmarks/probe_lstm.py

# 3. CTR stage probe (steady-state attribution after the recompile fix)
run probe_ctr 1200 python benchmarks/probe_ctr.py

# 4. cheap suite rows: smallnet, trainer-loop overhead (a round-1
#    acceptance criterion), transformer LM at 8k + its SWA variant
run suite_small 2400 python benchmarks/suite.py --only smallnet,trainer_loop
run suite_misc 2400 python benchmarks/suite.py --only transformer

# 5. the north stars + decode greedy + headline resnet, driver-format
#    (bench.py worst case ~6270s incl. its own liveness gate)
run bench 6300 python bench.py

# 5b. decode modes: greedy/sample/beam/gqa/int8 + long-horizon SWA +
#     speculative (perfect/small-draft/sampled) — each row prints the
#     moment it's measured, so a late-mode hang loses nothing
run suite_decode 3000 python benchmarks/suite.py --only decode

# 6. image suite; big-batch rows are the wedge risk so they go last,
#    one model per stage
run suite_alexnet 1800 python benchmarks/suite.py --only alexnet --batches 64,128,256
run suite_googlenet 1800 python benchmarks/suite.py --only googlenet
run suite_resnet 1800 python benchmarks/suite.py --only resnet50
run suite_resnet_s2d 1800 python benchmarks/suite.py --only resnet50_s2d
run suite_resnet_remat 1800 python benchmarks/suite.py --only resnet50_remat --batches 64,256
run suite_resnet_remat_full 1800 python benchmarks/suite.py --only resnet50_remat_full --batches 64,256
run suite_vgg 1800 python benchmarks/suite.py --only vgg19

# 6b. MoE transformer row (opt-in bench)
run suite_moe 1800 python benchmarks/suite.py --only moe

# 7. refreshed profile traces for PROFILE_NOTES: the headline resnet
#    step and the googlenet MFU floor (r3 verdict #8: trace or number)
run profile 1200 python benchmarks/profile_step.py --batch 256 --iters 10
run profile_googlenet 1200 python benchmarks/profile_step.py --model googlenet --batch 256 --iters 10

# 8. the single biggest compile (alexnet bs512) dead last: if it wedges
#    the chip nothing is behind it
run suite_alexnet512 1800 python benchmarks/suite.py --only alexnet --batches 512

echo "=== done ($(date +%H:%M:%S)) — logs in benchmarks/r5_logs/ ==="
