#!/bin/bash
# Round-5 tail: the LSTM measurements, requeued AFTER every other stage
# has its number. suite_lstm hung >20 min through the relay at 08:36
# (first-ever remote Mosaic compile of the fused Pallas LSTM kernel —
# the GRU kernel and flash attention compiled fine in r1/r3) and its
# timeout kill wedged the chip, so the LSTM rows now run:
#   1. with PADDLE_TPU_RNN_IMPL=xla — the safe scan path (r1 parity,
#      answers the h256/h512 inversion question, cannot hang),
#   2. ONE guarded fused-kernel attempt DEAD LAST: if it wedges the
#      relay, nothing is behind it.
set -u
cd "$(dirname "$0")/.."
. benchmarks/r5_common.sh
mkdir -p benchmarks/r5_logs

# wait for the addendum (which itself waits for the main campaign)
while ! grep -q "addendum done\|addendum aborted\|still running at STOP_EPOCH" \
        benchmarks/r5_logs/addendum_console.txt 2>/dev/null; do
  if [ "$(date +%s)" -ge "$STOP_EPOCH" ]; then
    echo "=== addendum still waiting at STOP_EPOCH — tail aborted ==="
    exit 0
  fi
  sleep 60
done

wait_alive() {
  while true; do
    if [ "$(date +%s)" -ge "$STOP_EPOCH" ]; then
      echo "=== chip still wedged at STOP_EPOCH — aborting tail ==="
      exit 0
    fi
    if chip_probe >> benchmarks/r5_logs/realive.log 2>&1; then
      echo "    (chip alive again $(date +%H:%M:%S))"
      return
    fi
    echo "    (chip not answering, re-probe in 300s)"
    sleep 300
  done
}

run() {  # name timeout cmd...
  local name=$1 tmo=$2; shift 2
  local now=$(date +%s)
  if [ "$now" -ge "$STOP_EPOCH" ]; then
    echo "=== $name SKIPPED (past STOP_EPOCH) ==="
    return
  fi
  local budget=$(( STOP_EPOCH - now ))
  if [ "$tmo" -gt "$budget" ]; then tmo=$budget; fi
  echo "=== $name ($(date +%H:%M:%S), budget ${tmo}s) ==="
  timeout "$tmo" "$@" > "benchmarks/r5_logs/$name.out" 2> "benchmarks/r5_logs/$name.err"
  local rc=$?
  echo "    rc=$rc  (tail of out:)"; tail -3 "benchmarks/r5_logs/$name.out" | sed 's/^/    /'
  if [ "$rc" = 124 ]; then
    wait_alive
  fi
}

echo "=== tail probe ($(date +%H:%M:%S)) ==="
chip_probe > benchmarks/r5_logs/tail_probe.out 2> benchmarks/r5_logs/tail_probe.err \
  || wait_alive

# 1. lstm suite rows on the scan path (r1-comparable; the instrumented
#    bench_lstm progress lines localize any residual hang)
run suite_lstm_xla 1500 env PADDLE_TPU_RNN_IMPL=xla \
  python benchmarks/suite.py --only lstm_h256,lstm_h512

# 2. the h256/h512 inversion probe, scan path (the r1 inversion was
#    measured on this path, so this is the diagnosis that matters)
run probe_lstm_xla 1500 env PADDLE_TPU_RNN_IMPL=xla PROBE_LSTM_ARMED=1 \
  python benchmarks/probe_lstm.py

# 3. the big lstm rows from the published table (h1280, b128)
run suite_lstm_big_xla 1500 env PADDLE_TPU_RNN_IMPL=xla \
  python benchmarks/suite.py --only lstm_h1280

# 4. ONE fused-kernel attempt, dead last, generous budget: either the
#    remote Mosaic compile finishes (and the fused-vs-scan A/B lands)
#    or this wedges the relay with nothing behind it
run suite_lstm_pallas 2400 env PADDLE_TPU_RNN_IMPL=pallas \
  python benchmarks/suite.py --only lstm_h256

echo "=== tail done ($(date +%H:%M:%S)) ==="
