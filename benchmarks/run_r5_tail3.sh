#!/bin/bash
# Round-5 tail-3: seq2seq fused-CE A/B against the north-star row
# (same shapes; the delta is the chunked CE over the 30k-vocab decoder
# head). Chains behind run_r5_tail2.sh; same wedge discipline.
set -u
cd "$(dirname "$0")/.."
. benchmarks/r5_common.sh
mkdir -p benchmarks/r5_logs

while ! grep -q "tail2 done\|tail2 aborted\|chip not answering" \
        benchmarks/r5_logs/tail2_console.txt 2>/dev/null; do
  if [ "$(date +%s)" -ge "$STOP_EPOCH" ]; then
    echo "=== tail2 still waiting at STOP_EPOCH — tail3 aborted ==="
    exit 0
  fi
  sleep 60
done

run() {  # name timeout cmd...
  local name=$1 tmo=$2; shift 2
  local now=$(date +%s)
  if [ "$now" -ge "$STOP_EPOCH" ]; then
    echo "=== $name SKIPPED (past STOP_EPOCH) ==="
    return
  fi
  local budget=$(( STOP_EPOCH - now ))
  if [ "$tmo" -gt "$budget" ]; then tmo=$budget; fi
  echo "=== $name ($(date +%H:%M:%S), budget ${tmo}s) ==="
  timeout "$tmo" "$@" > "benchmarks/r5_logs/$name.out" 2> "benchmarks/r5_logs/$name.err"
  local rc=$?
  echo "    rc=$rc  (tail of out:)"; tail -3 "benchmarks/r5_logs/$name.out" | sed 's/^/    /'
}

echo "=== tail3 probe ($(date +%H:%M:%S)) ==="
chip_probe > benchmarks/r5_logs/tail3_probe.out 2> benchmarks/r5_logs/tail3_probe.err \
  || { echo "chip not answering — tail3 aborted"; exit 0; }

# seq2seq fused-CE A/B (the plain row re-measures in the same process
# conditions so the pair is apples-to-apples)
run suite_seq2seq_fused 2800 python benchmarks/suite.py --only seq2seq,seq2seq_fused_ce

echo "=== tail3 done ($(date +%H:%M:%S)) ==="
