#!/bin/bash
# Round-4 chip measurement campaign.
#
# Inherits the r3b wedge lessons (see run_r3_measurements.sh header):
# cheap compiles first, pool A/B early, subprocess-isolated stages,
# big-batch image rows last.  New in r4:
#   * STOP_EPOCH (unix seconds): stages are SKIPPED once past it, so a
#     campaign that starts late never overruns into the driver's own
#     end-of-round bench run (the r3 watcher only gated *starting* the
#     campaign; a late start could still have collided).
#   * remat/fusion A-B rows for the HBM-roofline work (resnet50_remat).
set -u
cd "$(dirname "$0")/.."
. benchmarks/r4_common.sh   # STOP_EPOCH + chip_probe (shared w/ watcher)
mkdir -p benchmarks/r4_logs

# a stage killed at its timeout may have wedged the relay (the r3
# hazard: a killed claimant wedges the chip ~2h) — launching the next
# stage into a wedged chip just burns its full timeout and re-wedges.
# After any rc=124, hold here re-probing until the chip answers again
# (or STOP_EPOCH passes, which aborts the campaign).
wait_alive() {
  while true; do
    if [ "$(date +%s)" -ge "$STOP_EPOCH" ]; then
      echo "=== chip still wedged at STOP_EPOCH — aborting campaign ==="
      exit 0
    fi
    if chip_probe >> benchmarks/r4_logs/realive.log 2>&1; then
      echo "    (chip alive again $(date +%H:%M:%S))"
      return
    fi
    echo "    (chip not answering, re-probe in 300s)"
    sleep 300
  done
}

run() {  # name timeout cmd...
  local name=$1 tmo=$2; shift 2
  local now=$(date +%s)
  if [ "$now" -ge "$STOP_EPOCH" ]; then
    echo "=== $name SKIPPED (past STOP_EPOCH) ==="
    return
  fi
  # cap the stage budget at the deadline: a stage launched shortly
  # before STOP_EPOCH must not run its full timeout past it and
  # collide with the driver's own bench on the single chip claim
  local budget=$(( STOP_EPOCH - now ))
  if [ "$tmo" -gt "$budget" ]; then tmo=$budget; fi
  echo "=== $name ($(date +%H:%M:%S), budget ${tmo}s) ==="
  timeout "$tmo" "$@" > "benchmarks/r4_logs/$name.out" 2> "benchmarks/r4_logs/$name.err"
  local rc=$?
  echo "    rc=$rc  (tail of out:)"; tail -3 "benchmarks/r4_logs/$name.out" | sed 's/^/    /'
  if [ "$rc" = 124 ]; then
    wait_alive
  fi
}

# 0. liveness (same criterion as the watcher/wait_alive)
echo "=== probe ($(date +%H:%M:%S)) ==="
chip_probe > benchmarks/r4_logs/probe.out 2> benchmarks/r4_logs/probe.err \
  || wait_alive

# 1. the open regression question: tie-split vs select-and-scatter
#    maxpool backward, resnet bs64 (cheap compile, done twice)
run probe_pool 1500 python benchmarks/probe_pool.py

# 1b. the round's key perf question at its cheapest shape: remat A/B
#     at bs64 (full bs-256 rows run later in stage 6; this early row
#     survives even if a later compile wedges the chip)
run probe_remat 2400 python benchmarks/suite.py --only resnet50,resnet50_remat,resnet50_remat_full --batches 64

# 2. lstm benches (fused kernel) + the h256/h512 inversion probe
run suite_lstm 1200 python benchmarks/suite.py --only lstm_h256,lstm_h512
run probe_lstm 1200 python benchmarks/probe_lstm.py

# 3. CTR stage probe (steady-state attribution after the recompile fix)
run probe_ctr 1200 python benchmarks/probe_ctr.py

# 4. cheap suite rows: smallnet, trainer-loop overhead, transformer
run suite_small 2400 python benchmarks/suite.py --only smallnet,trainer_loop
run suite_misc 2400 python benchmarks/suite.py --only transformer

# 5. the north stars + decode, driver-format (resnet bs256 inside,
#    isolated+retry; worst case 6060s — see bench.py main's budget)
run bench 6300 python bench.py

# 6. image suite, batch-ascending; big-batch rows are the wedge risk so
#    they go last, one stage each
run suite_alexnet 1800 python benchmarks/suite.py --only alexnet --batches 64,128,256
run suite_googlenet 1800 python benchmarks/suite.py --only googlenet
run suite_resnet 1800 python benchmarks/suite.py --only resnet50
run suite_resnet_s2d 1800 python benchmarks/suite.py --only resnet50_s2d
run suite_resnet_remat 1800 python benchmarks/suite.py --only resnet50_remat --batches 64,256
run suite_resnet_remat_full 1800 python benchmarks/suite.py --only resnet50_remat_full --batches 64,256
run suite_vgg 1800 python benchmarks/suite.py --only vgg19

# 6b. MoE transformer row (opt-in bench; T=2048 compiles small)
run suite_moe 1800 python benchmarks/suite.py --only moe

# 6c. KV-cache decode throughput (serving latency analog)
run suite_decode 1800 python benchmarks/suite.py --only decode

# 7. refreshed profile traces for PROFILE_NOTES: the headline resnet
#    step (now with the remat A/B interesting) and the googlenet MFU
#    floor (VERDICT r3 #8: 10-19% MFU, 3x below VGG — trace or number)
run profile 1200 python benchmarks/profile_step.py --batch 256 --iters 10
run profile_googlenet 1200 python benchmarks/profile_step.py --model googlenet --batch 256 --iters 10

# 8. the single biggest compile (alexnet bs512) dead last: if it wedges
#    the chip nothing is behind it
run suite_alexnet512 1800 python benchmarks/suite.py --only alexnet --batches 512

echo "=== done ($(date +%H:%M:%S)) — logs in benchmarks/r4_logs/ ==="
