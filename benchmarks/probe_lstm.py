"""Isolate the lstm h256-slower-than-h512 inversion (VERDICT r2 weak #4).

Times three nested slices of the lstm_h{256,512} suite bench on the
chip, at several hidden sizes, so the inversion (if it survives the
round-3 input-projection hoisting) can be attributed to a specific
stage:

  1. the bare recurrence: scan of h@W_hh + gate math over T steps
  2. the full lstm() op (hoisted input projection + scan)
  3. the full 2-layer classifier train step (the suite bench)

Usage: python benchmarks/probe_lstm.py [--iters 20]
"""

from __future__ import annotations

# --- r5 campaign guard -------------------------------------------------
# suite_lstm (the bench this probe explains) HUNG through the axon relay
# at 08:36-08:55 UTC and its SIGTERM re-wedged the chip (r3 hazard).
# Until the hang is localized (instrumented bench_lstm progress lines),
# this probe must not repeat the same claim-and-hang: it would re-wedge
# the relay right as wait_alive recovers it, ahead of the north-star and
# headline stages. The lstm diagnostics are requeued in
# run_r5_tail.sh AFTER every other stage has its number.
import os as _os
if _os.environ.get("PROBE_LSTM_ARMED") != "1":
    print("probe_lstm: DISARMED for the r5 main campaign "
          "(suite_lstm wedged the relay; see results_v5e1.md r5). "
          "Set PROBE_LSTM_ARMED=1 to run.", flush=True)
    raise SystemExit(0)
# -----------------------------------------------------------------------


import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

from benchmarks.suite import timeit


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--seq", type=int, default=100)
    args = ap.parse_args()

    from paddle_tpu.core import devices as dev_lib
    from paddle_tpu.core import dtypes
    from paddle_tpu.ops import rnn as rnn_ops

    # fail fast (exit 3) on a wedged relay instead of hanging until the
    # campaign stage timeout reaps us
    dev_lib.init_devices_or_die()
    dtypes.set_default_policy(dtypes.bf16_compute_policy())
    b, t = args.batch, args.seq

    for hidden in (128, 256, 384, 512, 768):
        params = rnn_ops.init_lstm_params(jax.random.key(0), hidden, hidden)
        x = jnp.asarray(
            np.random.RandomState(0).randn(b, t, hidden), jnp.float32)

        @jax.jit
        def bare_scan(params, x_proj):
            def step(state, xp):
                s = rnn_ops.lstm_step_from_proj(params, xp, state)
                return s, s.h
            h0 = rnn_ops.LSTMState(
                jnp.zeros((b, hidden), x_proj.dtype),
                jnp.zeros((b, hidden), x_proj.dtype))
            _, hs = jax.lax.scan(step, h0, x_proj.transpose(1, 0, 2))
            return hs

        @jax.jit
        def full_lstm(params, x):
            out, _ = rnn_ops.lstm(params, x)
            return out

        x_proj = jnp.asarray(np.random.RandomState(1).randn(b, t, 4 * hidden),
                             jnp.bfloat16)
        ms_scan = timeit(bare_scan, params, x_proj, iters=args.iters)
        ms_lstm = timeit(full_lstm, params, x, iters=args.iters)
        line = (f"hidden={hidden:4d}  bare_scan={ms_scan:7.2f} ms  "
                f"full_lstm={ms_lstm:7.2f} ms")
        if hidden in (256, 512):
            # stage 3: the suite's full 2-layer classifier train step —
            # localizes the inversion between the lstm op and the rest
            from benchmarks.suite import bench_lstm
            ms_full = bench_lstm(hidden, b, seq_len=t,
                                 iters=args.iters) * 1000
            line += f"  classifier_step={ms_full:7.2f} ms"
        print(line, flush=True)


if __name__ == "__main__":
    main()
