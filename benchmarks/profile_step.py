"""Capture and summarize a TPU profile of the ResNet-50 train step.

Usage: python benchmarks/profile_step.py [--batch 256] [--model resnet50]

Dumps a jax.profiler trace, then parses the xplane with xprof's converter
to print the top self-time ops — the evidence the MFU work (VERDICT round
1 item 1) is driven by.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

# mirror a cpu request into jax config (the TPU plugin force-selects its
# platform at config level) — a cpu tooling-validation run must never
# try to claim the real chip
if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np


def build_step(name: str, batch: int, hw: int = 224):
    from paddle_tpu import optim
    from paddle_tpu.core import dtypes
    from paddle_tpu.nn.module import ShapeSpec
    from paddle_tpu.ops import losses
    from paddle_tpu.train.state import TrainState
    from paddle_tpu.train.trainer import make_train_step

    dtypes.set_default_policy(dtypes.bf16_compute_policy())
    from benchmarks.suite import _image_model

    model = _image_model(name)
    rng = jax.random.key(0)
    params, mstate = model.init(rng, ShapeSpec((batch, hw, hw, 3)))
    opt = optim.momentum(0.1, mu=0.9)
    state = TrainState.create(params, mstate, opt)
    step = make_train_step(
        model, lambda lo, la: jnp.mean(losses.softmax_cross_entropy(lo, la)),
        opt, donate=True)
    x = jnp.asarray(np.random.RandomState(0).rand(batch, hw, hw, 3), jnp.float32)
    y = jnp.asarray(np.random.RandomState(1).randint(0, 1000, batch))
    return step, state, rng, x, y


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--model", default="resnet50")
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--logdir", default="/tmp/pt_trace")
    args = ap.parse_args()

    from paddle_tpu.core import devices as dev_lib

    # fail fast (exit 3) on a wedged relay instead of hanging
    dev_lib.init_devices_or_die()
    step, state, rng, x, y = build_step(args.model, args.batch)
    state, loss, _ = step(state, rng, (x,), (y,))
    float(loss)
    t0 = time.perf_counter()
    for _ in range(args.iters):
        state, loss, _ = step(state, rng, (x,), (y,))
    float(loss)
    dt = (time.perf_counter() - t0) / args.iters
    print(f"ms/batch={1000*dt:.2f} imgs/sec={args.batch/dt:.1f}")

    os.makedirs(args.logdir, exist_ok=True)
    with jax.profiler.trace(args.logdir):
        for _ in range(args.iters):
            state, loss, _ = step(state, rng, (x,), (y,))
        float(loss)

    planes = sorted(glob.glob(args.logdir + "/**/*.xplane.pb", recursive=True))
    if not planes:
        print("no xplane captured", file=sys.stderr)
        return
    plane = planes[-1]
    from xprof.convert import raw_to_tool_data as rtd

    data, _ = rtd.xspace_to_tool_data([plane], "hlo_stats", {})
    if isinstance(data, bytes):
        data = data.decode()
    parsed = json.loads(data)
    tbl = parsed[0] if isinstance(parsed, list) else parsed
    rows = [[c["v"] for c in r["c"]] for r in tbl["rows"]]
    # columns (xprof hlo_stats): 2=category, 4=op text, 9=total self us,
    # 14=model GFLOP/s, 17=HBM GiB/s, 21=bound-by
    rows.sort(key=lambda r: -r[9])
    total_us = sum(r[9] for r in rows)
    print(f"device total: {total_us / 1000 / args.iters:.2f} ms/step")
    print(f"{'self_ms/step':>12s} {'%':>5s} {'GFLOP/s':>8s} {'GiB/s':>7s} "
          f"{'bound':>6s}  op")
    for r in rows[:25]:
        txt = str(r[4])[:90].replace("\n", " ")
        print(f"{r[9] / 1000 / args.iters:12.3f} {100 * r[9] / total_us:5.1f} "
              f"{r[14]:8.0f} {r[17]:7.0f} {str(r[21]):>6s}  {txt}")


if __name__ == "__main__":
    main()
