# Shared definitions for the r4 watcher + campaign (sourced, not run).
#
# STOP_EPOCH: unix time after which no chip work may start (and running
# stages are capped) so the driver's end-of-round bench owns the claim.
export STOP_EPOCH=${STOP_EPOCH:-1785555000}   # 2026-08-01 03:30 UTC

# One liveness criterion everywhere: the tiny matmul must complete AND
# the backend must be the chip (platform "axon" through the relay; a
# silent CPU fallback would otherwise declare a wedged chip alive and
# launch the next heavy stage into it).
#
# 600s probe budget, NOT 150: the r3+r4 wedge persisted for 16+ hours
# under a 150s/5-min prober — consistent with each killed probe
# grabbing the claim the moment the previous wedge expires and being
# SIGTERMed mid-init, re-wedging the relay for another window. A probe
# long enough to ride out a slow grant (+ the ~30s compile) breaks
# that cycle instead of perpetuating it.
chip_probe() {
  timeout 600 python -c "
import jax, jax.numpy as jnp
assert jax.default_backend() != 'cpu', jax.default_backend()
print((jnp.ones((128,128),jnp.bfloat16)@jnp.ones((128,128),jnp.bfloat16))[0,0])
"
}
