# Shared definitions for the r4 watcher + campaign (sourced, not run).
#
# STOP_EPOCH: unix time after which no chip work may start (and running
# stages are capped) so the driver's end-of-round bench owns the claim.
export STOP_EPOCH=${STOP_EPOCH:-1785555000}   # 2026-08-01 03:30 UTC

# One liveness criterion everywhere: the tiny matmul must complete AND
# the backend must be the chip (platform "axon" through the relay; a
# silent CPU fallback would otherwise declare a wedged chip alive and
# launch the next heavy stage into it).
chip_probe() {
  timeout 150 python -c "
import jax, jax.numpy as jnp
assert jax.default_backend() != 'cpu', jax.default_backend()
print((jnp.ones((128,128),jnp.bfloat16)@jnp.ones((128,128),jnp.bfloat16))[0,0])
"
}
