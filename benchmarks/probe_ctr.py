"""Stage-isolated timing of the CTR sparse train step (north star #2).

The round-3 chip measurement gave 772 ms/batch at bs4096 x 32 slots
(679k rows/sec). This breaks the step into stages so the dominant cost
(lookup gather vs MLP fwd/bwd vs row-grad merge vs scatter-add update)
is attributable.

Usage: python benchmarks/probe_ctr.py [--batch 4096]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

from benchmarks.suite import timeit


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=4096)
    ap.add_argument("--slots", type=int, default=32)
    ap.add_argument("--vocab", type=int, default=1_000_000)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--iters", type=int, default=20)
    args = ap.parse_args()

    from paddle_tpu import optim
    from paddle_tpu.core import devices as dev_lib
    from paddle_tpu.core import mesh as mesh_lib
    from paddle_tpu.models.ctr import CTRModel

    # fail fast (exit 3) on a wedged relay instead of hanging
    n_dev = len(dev_lib.init_devices_or_die())
    mesh = mesh_lib.build_mesh(mesh_lib.MeshConfig(data=1, model=n_dev))
    model = CTRModel(vocab=args.vocab, embed_dim=args.dim, mesh=mesh)
    r = np.random.RandomState(0)
    params, mlp_state = model.init(jax.random.key(0), args.batch, args.slots)
    ids = jnp.asarray(r.randint(0, args.vocab, (args.batch, args.slots)),
                      jnp.int32)
    labels = jnp.asarray(r.randint(0, 2, args.batch), jnp.int32)
    flat = ids.reshape(-1)
    lr = jnp.asarray(0.05, jnp.float32)

    # stage 1: gather only
    @jax.jit
    def lookup(table, flat):
        return model._lookup(model.table, table, flat)

    ms = timeit(lookup, params["deep"], flat, iters=args.iters)
    print(f"lookup(deep) [K={flat.shape[0]} D={args.dim}]: {ms:8.2f} ms",
          flush=True)

    # stage 2: full forward
    @jax.jit
    def fwd(params, ids):
        return model.apply(params, mlp_state, ids)

    ms = timeit(fwd, params, ids, iters=args.iters)
    print(f"forward:                                {ms:8.2f} ms", flush=True)

    # stage 3: scatter-add update only (row grads precomputed)
    row_g = jnp.asarray(r.randn(flat.shape[0], args.dim) * 0.01,
                        jnp.float32)

    @jax.jit
    def push(table, flat, row_g):
        if model._use_alltoall(flat.shape[0]):
            return model.table.alltoall_push_row_grads(table, flat, row_g, lr)
        return model.table.apply_row_grads(table, flat, row_g, lr)

    ms = timeit(push, params["deep"], flat, row_g, iters=args.iters)
    print(f"row-grad push(deep):                    {ms:8.2f} ms", flush=True)

    # stage 4: the full train step (the bench's number)
    opt = optim.adam(1e-3)
    opt_state = opt.init(params["mlp"])
    step = model.make_train_step(opt, mlp_state)

    def full(params, opt_state):
        p, o, loss = step(params, opt_state, ids, labels, lr,
                          jnp.zeros((), jnp.int32), jax.random.key(1))
        return p, o, loss

    # TWO warmups (as suite.bench_ctr_sparse): if the aval-mismatch
    # recompile this probe exists to diagnose regresses, it must land
    # BEFORE timing so the stage numbers stay attributable
    out = full(params, opt_state)
    out = full(*out[:2])
    jax.block_until_ready(out[0])
    t0 = time.perf_counter()
    for _ in range(args.iters):
        out = full(*out[:2])
    jax.block_until_ready(out[0])
    ms = (time.perf_counter() - t0) / args.iters * 1000
    print(f"full train step:                        {ms:8.2f} ms", flush=True)


if __name__ == "__main__":
    main()
