#!/bin/bash
# Probe the chip every PERIOD seconds; the moment a tiny matmul
# completes, fire the r3b measurement campaign once and exit.
# Each probe runs in its own subprocess under `timeout` — a wedged
# relay makes the probe hang, the timeout reaps it, we sleep and retry.
set -u
cd "$(dirname "$0")/.."
PERIOD=${PERIOD:-300}
# don't START the campaign close to round end: the driver's own bench
# run needs the single chip claim; a campaign mid-flight would starve it
DEADLINE=${DEADLINE:-1410}   # HHMM local
LOG=benchmarks/r3_logs/watcher.log
mkdir -p benchmarks/r3_logs

while true; do
  if [ "$(date +%H%M)" -ge "$DEADLINE" ]; then
    echo "[watcher $(date +%H:%M:%S)] past deadline $DEADLINE — standing down so the driver's bench owns the chip" | tee -a "$LOG"
    exit 0
  fi
  if timeout 150 python -c "import jax, jax.numpy as jnp; print((jnp.ones((128,128),jnp.bfloat16)@jnp.ones((128,128),jnp.bfloat16))[0,0])" \
       >> "$LOG" 2>&1; then
    echo "[watcher $(date +%H:%M:%S)] chip ANSWERED — firing campaign" | tee -a "$LOG"
    bash benchmarks/run_r3_measurements.sh 2>&1 | tee -a benchmarks/r3_logs/campaign_console.txt
    exit 0
  fi
  echo "[watcher $(date +%H:%M:%S)] chip still wedged; retry in ${PERIOD}s" >> "$LOG"
  sleep "$PERIOD"
done
