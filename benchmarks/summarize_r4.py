"""Collect every JSON record the r4 campaign produced into one markdown
table — run after (or during) `run_r4_measurements.sh` to refresh
`results_v5e1.md` quickly. No jax import: safe anywhere.

Usage: python benchmarks/summarize_r4.py [--dir benchmarks/r4_logs]
"""

from __future__ import annotations

import argparse
import json
import pathlib


def collect(log_dir: pathlib.Path):
    recs = []
    for path in sorted(log_dir.glob("*.out")):
        for line in path.read_text().splitlines():
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            rec["_stage"] = path.stem
            recs.append(rec)
    return recs


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dir", default="benchmarks/r4_logs")
    args = ap.parse_args()
    recs = collect(pathlib.Path(args.dir))
    if not recs:
        print("(no JSON records found yet)")
        return
    print("| stage | bench/metric | key numbers |")
    print("|---|---|---|")
    for r in recs:
        stage = r.pop("_stage")
        name = r.pop("bench", None) or r.pop("metric", None) \
            or r.pop("probe", "?")
        nums = ", ".join(f"{k}={v}" for k, v in r.items()
                         if isinstance(v, (int, float)))
        print(f"| {stage} | {name} | {nums} |")


if __name__ == "__main__":
    main()
