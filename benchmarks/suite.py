"""Benchmark suite reproducing the reference's published benchmark
configs (reference: benchmark/README.md — AlexNet/GoogleNet/VGG/ResNet
ms/batch at batch 64/128/256 on K40m; benchmark/rnn/rnn.py LSTM
text-classification ms/batch at hidden 256/512; CPU tables in
IntelOptimizedPaddle.md). Prints one JSON line per config:

  {"bench": ..., "batch": ..., "ms_per_batch": ..., "imgs_per_sec": ...,
   "ref_ms_per_batch": ..., "speedup_vs_ref": ...}

Run: python benchmarks/suite.py [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def timeit(fn, *args, iters=20, warmup=2):
    """Shared bench timing: warm up (TWICE by default — the second call
    catches input-vs-output aval-mismatch recompiles, see
    bench_ctr_sparse), then average iters synced calls."""
    import jax as _jax

    for _ in range(max(warmup, 1)):  # at least once: `out` must exist
        out = fn(*args)
    _jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    _jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters * 1000


def progress(msg: str) -> None:
    """Per-stage progress to stderr (stdout stays JSON-only) so a stalled
    run is diagnosable — VERDICT r2 weak #2: the benches printed nothing
    until fully done."""
    print(f"[suite {time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr,
          flush=True)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

# the TPU plugin force-selects its platform at config level, outranking
# JAX_PLATFORMS — mirror a cpu request into the config so a cpu smoke
# run never claims the chip (same pattern as __graft_entry__)
if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

REF = {
    # reference numbers: ms/batch on 1x K40m (benchmark/README.md:33-58)
    ("alexnet", 64): 195.0, ("alexnet", 128): 334.0, ("alexnet", 256): 602.0,
    ("alexnet", 512): 1629.0,
    ("googlenet", 64): 613.0, ("googlenet", 128): 1149.0,
    ("googlenet", 256): 2348.0,
    # CPU tables (IntelOptimizedPaddle.md): imgs/sec -> ms/batch
    ("vgg19", 64): 64 / 28.5 * 1000, ("vgg19", 128): 128 / 29.8 * 1000,
    ("resnet50", 64): 64 / 81.7 * 1000, ("resnet50", 128): 128 / 82.4 * 1000,
    ("resnet50", 256): 256 / 84.1 * 1000,
    # LSTM text classification, hidden 256/512/1280 at bs 64 and 128
    # (README.md:115-126)
    ("lstm_h256", 64): 83.0, ("lstm_h512", 64): 184.0,
    ("lstm_h1280", 64): 641.0,
    ("lstm_h256", 128): 110.0, ("lstm_h512", 128): 261.0,
    # SmallNet CIFAR-quick, 32x32 (README.md:54-58)
    ("smallnet", 64): 10.463, ("smallnet", 128): 18.184,
    ("smallnet", 256): 33.113, ("smallnet", 512): 63.039,
}

# hardware constants + analytic per-image FLOPs live in ONE place
# shared with bench.py's headline MFU math (paddle_tpu/core/hw.py)
from paddle_tpu.core.hw import (  # noqa: E402
    FWD_GFLOPS, V5E_HBM_GBPS, V5E_PEAK_TFLOPS)


def _image_model(name):
    from paddle_tpu import models

    if name == "alexnet":
        return models.alexnet.alexnet(num_classes=1000)
    if name == "googlenet":
        return models.googlenet.googlenet(num_classes=1000)
    if name == "vgg19":
        return models.vgg.vgg(19, num_classes=1000)
    if name == "resnet50":
        return models.resnet.resnet(50, num_classes=1000)
    if name == "resnet50_s2d":
        # math-identical stem on a 2x2 space-to-depth blocking
        return models.resnet.resnet(50, num_classes=1000, s2d_stem=True)
    if name == "resnet50_remat":
        # save only conv outputs; recompute BN/ReLU in the backward
        # (HBM-bytes reduction — PROFILE_NOTES roofline attack)
        return models.resnet.resnet(50, num_classes=1000, remat="conv_out")
    if name == "resnet50_remat_full":
        # save nothing inside each block: max bytes reduction, +1 fwd
        # of recompute FLOPs (the MXU idles at ~39% so recompute is
        # cheaper than the bytes it saves if the roofline argument holds)
        return models.resnet.resnet(50, num_classes=1000, remat="full")
    if name == "smallnet":
        return models.smallnet.smallnet(num_classes=10)
    raise ValueError(name)


def bench_image(name: str, batch: int, *, hw: int = 224, iters: int = 20):
    from paddle_tpu import optim
    from paddle_tpu.nn.module import ShapeSpec
    from paddle_tpu.ops import losses
    from paddle_tpu.train.state import TrainState
    from paddle_tpu.train.trainer import make_train_step

    model = _image_model(name)
    rng = jax.random.key(0)
    params, mstate = model.init(rng, ShapeSpec((batch, hw, hw, 3)))
    opt = optim.momentum(0.1, mu=0.9)
    state = TrainState.create(params, mstate, opt)
    step = make_train_step(
        model, lambda lo, la: jnp.mean(losses.softmax_cross_entropy(lo, la)),
        opt, donate=True)
    x = jnp.asarray(np.random.RandomState(0).rand(batch, hw, hw, 3),
                    jnp.float32)
    n_classes = 10 if name == "smallnet" else 1000
    y = jnp.asarray(np.random.RandomState(1).randint(0, n_classes, batch))
    progress(f"image/{name}: warmup/compile (batch={batch} hw={hw})")
    state, loss, _ = step(state, rng, (x,), (y,))
    float(loss)
    progress(f"image/{name}: timing {iters} steps")
    t0 = time.perf_counter()
    for _ in range(iters):
        state, loss, _ = step(state, rng, (x,), (y,))
    float(loss)
    dt = (time.perf_counter() - t0) / iters
    progress(f"image/{name}: done ({1000*dt:.1f} ms/batch)")
    return dt


def bench_lstm(hidden: int, batch: int, *, seq_len: int = 100,
               vocab: int = 10000, iters: int = 20):
    """2-layer LSTM + fc text classifier (reference: benchmark/rnn/rnn.py
    with num_layer=2)."""
    from paddle_tpu import nn, optim
    from paddle_tpu.nn.module import ShapeSpec
    from paddle_tpu.ops import losses
    from paddle_tpu.train.state import TrainState
    from paddle_tpu.train.trainer import make_train_step

    model = nn.Sequential([
        nn.Embedding(vocab, hidden, name="emb"),
        nn.LSTM(hidden, name="lstm1"),
        nn.LSTM(hidden, name="lstm2"),
        nn.Lambda(lambda x: x.mean(axis=1), name="pool",
                  out_spec_fn=lambda s: ShapeSpec(
                      (s.shape[0], s.shape[2]), s.dtype)),
        nn.Dense(2, name="fc"),
    ])
    rng = jax.random.key(0)
    progress(f"lstm: eager param init (hidden={hidden})")
    params, mstate = model.init(
        rng, ShapeSpec((batch, seq_len), jnp.int32))
    jax.block_until_ready(params)
    progress("lstm: params ready; building train state")
    opt = optim.adam(1e-3)
    state = TrainState.create(params, mstate, opt)
    step = make_train_step(
        model, lambda lo, la: jnp.mean(losses.softmax_cross_entropy(lo, la)),
        opt, donate=True)
    x = jnp.asarray(np.random.RandomState(0).randint(
        0, vocab, (batch, seq_len)), jnp.int32)
    y = jnp.asarray(np.random.RandomState(1).randint(0, 2, batch))
    progress(f"lstm: warmup/compile (hidden={hidden} batch={batch})")
    state, loss, _ = step(state, rng, (x,), (y,))
    float(loss)
    progress(f"lstm: timing {iters} steps")
    t0 = time.perf_counter()
    for _ in range(iters):
        state, loss, _ = step(state, rng, (x,), (y,))
    float(loss)
    return (time.perf_counter() - t0) / iters


def bench_seq2seq(batch: int = 64, *, src_len: int = 30, tgt_len: int = 30,
                  hidden: int = 512, embed: int = 256, vocab: int = 30000,
                  iters: int = 20, fused_ce_chunk=None):
    """Seq2seq-attention NMT training throughput in target tokens/sec —
    the BASELINE.json north star the round-1 suite never measured
    (reference driver analog: benchmark/paddle/rnn/run.sh). Variable-
    length batches: lengths drawn uniformly from [len/2, len] with the
    dense batch padded to the max (the training pipeline's bucketed
    shape). MFU comes from XLA's own flop count for the compiled step.
    """
    from paddle_tpu.models import seq2seq_attn
    from paddle_tpu import optim

    rng = np.random.RandomState(0)
    params = seq2seq_attn.init_params(
        jax.random.key(0), vocab, vocab, embed_dim=embed, hidden=hidden)
    opt = optim.adam(1e-3)
    opt_state = opt.init(params)

    src = jnp.asarray(rng.randint(2, vocab, (batch, src_len)), jnp.int32)
    tgt = jnp.asarray(rng.randint(2, vocab, (batch, tgt_len)), jnp.int32)
    src_lens = jnp.asarray(rng.randint(src_len // 2, src_len + 1, batch))
    tgt_lens = jnp.asarray(rng.randint(tgt_len // 2, tgt_len + 1, batch))

    @jax.jit
    def step(params, opt_state, src, src_lens, tgt, tgt_lens):
        def loss_fn(p):
            return seq2seq_attn.loss(p, src, src_lens, tgt, tgt_lens,
                                     fused_ce_chunk=fused_ce_chunk)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        new_params, new_opt = opt.update(grads, opt_state, params,
                                         jnp.zeros((), jnp.int32))
        return new_params, new_opt, loss

    # AOT: lower+compile ONCE and execute the compiled object directly —
    # round 2 compiled here and then recompiled on the first step() call,
    # doubling an already-long scan compile (VERDICT r2 weak #2).
    progress(f"seq2seq: lowering (batch={batch} hidden={hidden})")
    lowered = step.lower(params, opt_state, src, src_lens, tgt, tgt_lens)
    progress("seq2seq: compiling")
    compiled = lowered.compile()
    flops = None
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):   # older jax: one entry
            cost = cost[0] if cost else {}    # per computation
        if cost and "flops" in cost:
            flops = float(cost["flops"])
    except Exception:
        pass

    progress("seq2seq: warmup step")
    params, opt_state, loss = compiled(params, opt_state, src, src_lens,
                                       tgt, tgt_lens)
    float(loss)
    progress(f"seq2seq: timing {iters} steps")
    t0 = time.perf_counter()
    for _ in range(iters):
        params, opt_state, loss = compiled(params, opt_state, src,
                                           src_lens, tgt, tgt_lens)
    float(loss)
    dt = (time.perf_counter() - t0) / iters
    progress(f"seq2seq: done ({1000*dt:.1f} ms/batch)")
    tokens = float(jnp.sum(tgt_lens))
    rec = {
        "bench": ("seq2seq_attn_fused_ce" if fused_ce_chunk
                  else "seq2seq_attn"), "batch": batch,
        **({"fused_ce_chunk": fused_ce_chunk} if fused_ce_chunk else {}),
        "hidden": hidden, "src_len": src_len, "tgt_len": tgt_len,
        "ms_per_batch": round(1000 * dt, 2),
        "tgt_tokens_per_sec": round(tokens / dt, 1),
    }
    if flops:
        rec["mfu_pct"] = round(100 * (flops / dt) / (V5E_PEAK_TFLOPS * 1e12),
                               1)
    return rec


def bench_ctr_sparse(batch: int = 4096, *, slots: int = 32,
                     vocab: int = 1_000_000, dim: int = 64,
                     iters: int = 20):
    """CTR sparse-embedding training throughput — the second unmeasured
    north star (BASELINE.json: 'sparse-embedding throughput via ICI
    all-to-all'). Reports rows exchanged/sec through one full train step
    (lookup + backward push on deep[dim]+wide[1] tables) and the
    effective row-gather bandwidth vs the chip's HBM peak.

    Runs on a model-axis mesh over ALL local devices (1 on a single
    chip — the exchange is then local; on a pod slice the same code
    measures the ICI path).
    """
    from paddle_tpu.core import mesh as mesh_lib
    from paddle_tpu.models.ctr import CTRModel
    from paddle_tpu import optim

    n_dev = len(jax.devices())
    mesh = mesh_lib.build_mesh(mesh_lib.MeshConfig(data=1, model=n_dev))
    model = CTRModel(vocab=vocab, embed_dim=dim, mesh=mesh)
    rng = np.random.RandomState(0)
    params, mlp_state = model.init(jax.random.key(0), batch, slots)
    opt = optim.adam(1e-3)
    opt_state = opt.init(params["mlp"])
    step = model.make_train_step(opt, mlp_state)

    ids = jnp.asarray(rng.randint(0, vocab, (batch, slots)), jnp.int32)
    labels = jnp.asarray(rng.randint(0, 2, batch), jnp.int32)
    lr = jnp.asarray(0.05, jnp.float32)
    step_i = jnp.zeros((), jnp.int32)

    progress(f"ctr: warmup/compile (batch={batch} vocab={vocab} "
             f"n_dev={n_dev})")
    # TWO warmup steps: the first compiles; the second would catch any
    # input-vs-output aval mismatch recompile (the bug that poisoned the
    # round-3 chip number — see test_ctr_step_compiles_once) instead of
    # letting it land inside the timed loop
    for _ in range(2):
        params, opt_state, loss = step(params, opt_state, ids, labels, lr,
                                       step_i, jax.random.key(1))
    float(loss)
    progress(f"ctr: timing {iters} steps")
    t0 = time.perf_counter()
    for _ in range(iters):
        params, opt_state, loss = step(params, opt_state, ids, labels, lr,
                                       step_i, jax.random.key(1))
    float(loss)
    dt = (time.perf_counter() - t0) / iters
    progress(f"ctr: done ({1000*dt:.1f} ms/batch)")
    # rows moved per step: deep + wide lookups AND their grad pushes
    rows = batch * slots * 2 * 2
    row_bytes = batch * slots * 2 * (dim + 1) * 4  # f32 vectors each way
    hbm_peak = V5E_HBM_GBPS * 1e9
    return {
        "bench": "ctr_sparse", "batch": batch, "slots": slots,
        "vocab": vocab, "dim": dim, "n_devices": n_dev,
        "ms_per_batch": round(1000 * dt, 2),
        "rows_per_sec": round(rows / dt, 1),
        "examples_per_sec": round(batch / dt, 1),
        "row_exchange_gbps": round(row_bytes / dt / 1e9, 2),
        "hbm_util_pct": round(100 * (row_bytes / dt) / hbm_peak, 2),
    }


def bench_transformer_lm(seq_len: int = 8192, *, batch: int = 4,
                         dim: int = 512, n_layers: int = 8, n_heads: int = 8,
                         vocab: int = 32000, iters: int = 10,
                         window=None, fused_ce_chunk=None):
    """Long-context transformer-LM training throughput (tokens/sec) —
    the framework's modern long-sequence story: Pallas flash attention +
    per-block remat. No reference counterpart (the reference predates
    transformers); the interesting axis is seq_len scaling, where dense
    attention would materialize a [T,T] score matrix per head."""
    from paddle_tpu import optim
    from paddle_tpu.models import transformer as T

    cfg = T.TransformerConfig(vocab=vocab, dim=dim, n_layers=n_layers,
                              n_heads=n_heads, attn_impl="auto",
                              attn_window=window, remat=True,
                              fused_ce_chunk=fused_ce_chunk)
    params = T.init_params(jax.random.key(0), cfg)
    opt = optim.adam(1e-3)
    opt_state = opt.init(params)
    toks = jnp.asarray(np.random.RandomState(0).randint(
        0, vocab, (batch, seq_len)), jnp.int32)

    @jax.jit
    def step(params, opt_state, toks):
        loss, grads = jax.value_and_grad(
            lambda p: T.loss(p, cfg, toks))(params)
        new_params, new_opt = opt.update(grads, opt_state, params,
                                         jnp.zeros((), jnp.int32))
        return new_params, new_opt, loss

    # AOT so XLA's own flop count of the compiled step feeds the mfu
    # field (r4 verdict weak #8: the north-star metric must come from
    # the driver-visible instrument, not hand math in the results doc)
    progress(f"transformer: lowering (T={seq_len} dim={dim} "
             f"L={n_layers})")
    lowered = step.lower(params, opt_state, toks)
    progress("transformer: compiling")
    compiled = lowered.compile()
    flops = None
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):   # older jax: one entry
            cost = cost[0] if cost else {}    # per computation
        if cost and "flops" in cost:
            flops = float(cost["flops"])
    except Exception:
        pass
    progress("transformer: warmup step")
    params, opt_state, loss = compiled(params, opt_state, toks)
    float(loss)
    progress(f"transformer: timing {iters} steps")
    t0 = time.perf_counter()
    for _ in range(iters):
        params, opt_state, loss = compiled(params, opt_state, toks)
    float(loss)
    dt = (time.perf_counter() - t0) / iters
    progress(f"transformer: done ({1000*dt:.1f} ms/batch)")
    rec = {
        "bench": ("transformer_lm_fused_ce" if fused_ce_chunk else
                  "transformer_lm" if window is None else
                  "transformer_lm_swa"),
        "window": window, "batch": batch, "seq_len": seq_len,
        "dim": dim, "n_layers": n_layers,
        **({"fused_ce_chunk": fused_ce_chunk} if fused_ce_chunk else {}),
        "ms_per_batch": round(1000 * dt, 2),
        "tokens_per_sec": round(batch * seq_len / dt, 1),
    }
    if flops:
        rec["mfu_pct"] = round(
            100 * (flops / dt) / (V5E_PEAK_TFLOPS * 1e12), 1)
    return rec


def bench_trainer_loop(name: str, batch: int, *, hw: int = 224,
                       iters: int = 20):
    """Same model/step as bench_image but THROUGH the Trainer event loop
    (lazy events; VERDICT round-1 weak #3 wanted this within ~5% of the
    raw jitted-step number)."""
    from paddle_tpu import optim
    from paddle_tpu.nn.module import ShapeSpec
    from paddle_tpu.ops import losses
    from paddle_tpu.train.trainer import Trainer

    model = _image_model(name)
    tr = Trainer(
        model, lambda lo, la: jnp.mean(losses.softmax_cross_entropy(lo, la)),
        optim.momentum(0.1, mu=0.9))
    state = tr.init_state(ShapeSpec((batch, hw, hw, 3)))
    x = jnp.asarray(np.random.RandomState(0).rand(batch, hw, hw, 3),
                    jnp.float32)
    y = jnp.asarray(np.random.RandomState(1).randint(0, 1000, batch))

    def batches(n):
        def factory():
            for _ in range(n):
                yield (x, y)
        return factory

    last_cost = []

    def handler(ev):
        # a real log_period-style handler: materialize only at the end
        from paddle_tpu.train import events as E
        if isinstance(ev, E.EndIteration) and ev.batch_id == iters - 1:
            last_cost.append(ev.cost)

    state = tr.train(state, batches(2), event_handler=handler)  # warmup
    float(state.step)  # drain the dispatch queue before timing
    t0 = time.perf_counter()
    state = tr.train(state, batches(iters), event_handler=handler)
    float(state.step)
    dt = (time.perf_counter() - t0) / iters
    return dt


def _init_devices_or_die(timeout_s: int = 600):
    from paddle_tpu.core.devices import init_devices_or_die as impl

    return impl(timeout_s, progress)


def bench_moe_lm(seq_len: int = 2048, *, batch: int = 8, dim: int = 512,
                 n_layers: int = 8, n_heads: int = 8, vocab: int = 32000,
                 experts: int = 8, iters: int = 10):
    """Sparsely-activated (MoE) transformer-LM training throughput.
    Every other block carries `experts` experts with top-2 routing —
    ~4x the FFN parameters of the dense model at roughly iso-FLOPs;
    the interesting number is tokens/sec vs the dense transformer row."""
    from paddle_tpu import optim
    from paddle_tpu.models import transformer as T

    cfg = T.TransformerConfig(vocab=vocab, dim=dim, n_layers=n_layers,
                              n_heads=n_heads, attn_impl="auto", remat=True,
                              moe_experts=experts)
    params = T.init_params(jax.random.key(0), cfg)
    n_params = sum(int(np.prod(x.shape))
                   for x in jax.tree_util.tree_leaves(params))
    opt = optim.adam(1e-3)
    opt_state = opt.init(params)
    toks = jnp.asarray(np.random.RandomState(0).randint(
        0, vocab, (batch, seq_len)), jnp.int32)

    @jax.jit
    def step(params, opt_state, toks):
        loss, grads = jax.value_and_grad(
            lambda p: T.loss(p, cfg, toks))(params)
        new_params, new_opt = opt.update(grads, opt_state, params,
                                         jnp.zeros((), jnp.int32))
        return new_params, new_opt, loss

    progress(f"moe: warmup/compile (T={seq_len} dim={dim} E={experts})")
    params, opt_state, loss = step(params, opt_state, toks)
    float(loss)
    progress(f"moe: timing {iters} steps")
    t0 = time.perf_counter()
    for _ in range(iters):
        params, opt_state, loss = step(params, opt_state, toks)
    float(loss)
    dt = (time.perf_counter() - t0) / iters
    progress(f"moe: done ({1000*dt:.1f} ms/batch)")
    return {
        "bench": "moe_transformer_lm", "batch": batch, "seq_len": seq_len,
        "dim": dim, "n_layers": n_layers, "experts": experts,
        "n_params": n_params,
        "ms_per_batch": round(1000 * dt, 2),
        "tokens_per_sec": round(batch * seq_len / dt, 1),
    }


def bench_decode(*, batch: int = 8, prompt_len: int = 128, steps: int = 128,
                 dim: int = 512, n_layers: int = 8, n_heads: int = 8,
                 vocab: int = 32000, iters: int = 5,
                 modes=("greedy", "sample", "beam", "gqa", "int8",
                        "int8kv", "spec", "swa")):
    """KV-cache decode throughput (new tokens/sec) per decode mode —
    the serving latency analog of the reference's C-API forward path
    (reference: capi/gradient_machine.h; the SequenceGenerator is the
    beam mode's ancestor — api/PaddleAPI.h:1025). No reference number
    exists; the rows track our own regression.

    PRINTS one JSON record per mode the moment that mode is measured —
    a later mode's hang (beam compiles a B*K-wide path) must not lose
    an already-produced metric (bench.py run_child's invariant)."""
    from paddle_tpu.models import transformer as T

    cfg = T.TransformerConfig(vocab=vocab, dim=dim, n_layers=n_layers,
                              n_heads=n_heads, attn_impl="dense")
    params = T.init_params(jax.random.key(0), cfg)
    prompt = jnp.asarray(np.random.RandomState(0).randint(
        0, vocab, (batch, prompt_len)), jnp.int32)
    base = {"batch": batch, "prompt_len": prompt_len, "steps": steps,
            "dim": dim, "n_layers": n_layers}

    def timed(label, fn, *args):
        progress(f"decode/{label}: warmup/compile (B={batch} "
                 f"T0={prompt_len} steps={steps})")
        out = fn(*args)
        jax.block_until_ready(out)
        progress(f"decode/{label}: timing {iters} runs")
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / iters
        progress(f"decode/{label}: done ({1000*dt:.1f} ms/run)")
        return dt

    if "greedy" in modes:
        gen = jax.jit(lambda p, toks: T.generate(p, cfg, toks,
                                                 steps=steps))
        dt = timed("greedy", gen, params, prompt)
        print(json.dumps({
            "bench": "decode", **base,
            "ms_per_decode": round(1000 * dt, 2),
            "new_tokens_per_sec": round(batch * steps / dt, 1),
            "ms_per_token_step": round(1000 * dt / steps, 3)}),
            flush=True)

    if "sample" in modes:
        samp = jax.jit(lambda p, toks, r: T.sample(
            p, cfg, toks, steps=steps, rng=r, temperature=0.8,
            top_p=0.95))
        dt = timed("sample", samp, params, prompt, jax.random.key(1))
        print(json.dumps({
            "bench": "decode_sample", **base,
            "temperature": 0.8, "top_p": 0.95,
            "new_tokens_per_sec": round(batch * steps / dt, 1)}),
            flush=True)

    if "beam" in modes:
        beam_n = 4
        beam = jax.jit(lambda p, toks: T.beam_decode(
            p, cfg, toks, steps=steps, beam_size=beam_n)[0])
        dt = timed(f"beam{beam_n}", beam, params, prompt)
        print(json.dumps({
            "bench": "decode_beam", **base, "beam_size": beam_n,
            # beam explores B*K hypotheses; counts kept tokens only
            "new_tokens_per_sec": round(batch * steps / dt, 1)}),
            flush=True)

    if "int8" in modes:
        # weight-only int8 (serve.quant): generate() traces the dequant
        # inside the scan body, so the loop streams s8 weights with the
        # convert+scale fused into each dot's operand read
        from paddle_tpu.serve import quant
        qp = quant.quantize_params(params)  # DEFAULT_MATCH kernels
        gen_q = jax.jit(lambda qp, toks: T.generate(
            qp, cfg, toks, steps=steps))
        dt = timed("int8", gen_q, qp, prompt)
        print(json.dumps({
            "bench": "decode_int8", **base,
            "new_tokens_per_sec": round(batch * steps / dt, 1)}),
            flush=True)

    if "int8kv" in modes:
        # int8 KV cache (r5): the cache is the decode-bandwidth term
        # that GROWS with context (weights are constant) — s8+scale
        # halves the bf16 cache bytes per step. Loop-state evidence:
        # tests/test_compiled_cost.py::TestInt8KVCacheState
        import dataclasses as _dc

        qkv_cfg = _dc.replace(cfg, kv_cache_dtype="int8")
        gen_kv = jax.jit(lambda p, toks: T.generate(
            p, qkv_cfg, toks, steps=steps))
        dt = timed("int8kv", gen_kv, params, prompt)
        print(json.dumps({
            "bench": "decode_int8kv", **base,
            "new_tokens_per_sec": round(batch * steps / dt, 1)}),
            flush=True)

    if "swa" in modes:
        # rolling-cache sliding-window decode (r5) at a LONG horizon,
        # paired with full attention at the SAME horizon: the ring
        # buffer makes per-step cache reads O(window) instead of
        # O(t0+steps), so the gap between these two rows is the
        # measurable win (and the memory gap is window/total)
        import dataclasses as _dc

        long_steps = steps * 8
        gen_full = jax.jit(lambda p, toks: T.generate(
            p, cfg, toks, steps=long_steps))
        dt = timed("long_full", gen_full, params, prompt)
        print(json.dumps({
            "bench": "decode_long", **base, "steps": long_steps,
            "new_tokens_per_sec": round(batch * long_steps / dt, 1)}),
            flush=True)
        wcfg = _dc.replace(cfg, attn_window=max(steps, 16))
        gen_w = jax.jit(lambda p, toks: T.generate(
            p, wcfg, toks, steps=long_steps))
        dt = timed("long_swa", gen_w, params, prompt)
        print(json.dumps({
            "bench": "decode_swa_long", **base, "steps": long_steps,
            "window": max(steps, 16),
            "new_tokens_per_sec": round(batch * long_steps / dt, 1)}),
            flush=True)

    if "spec" in modes:
        # batched speculative decoding (r5), two bracketing rows:
        # perfect draft (== target) is the amortization CEILING — every
        # round verifies K+1 tokens in one target forward; a small
        # random draft is the overhead FLOOR (near-zero acceptance)
        k = 4
        spec_p = jax.jit(lambda p, toks: T.speculative_generate(
            p, cfg, p, cfg, toks, steps=steps, draft_k=k))
        dt = timed("spec_perfect", spec_p, params, prompt)
        print(json.dumps({
            "bench": "decode_spec_perfect", **base, "draft_k": k,
            "new_tokens_per_sec": round(batch * steps / dt, 1)}),
            flush=True)
        dcfg = T.TransformerConfig(vocab=vocab, dim=max(dim // 4, 16),
                                   n_layers=2, n_heads=n_heads,
                                   attn_impl="dense")
        dparams = T.init_params(jax.random.key(7), dcfg)
        spec_s = jax.jit(lambda p, dp, toks: T.speculative_generate(
            p, cfg, dp, dcfg, toks, steps=steps, draft_k=k))
        dt = timed("spec_small_draft", spec_s, params, dparams, prompt)
        print(json.dumps({
            "bench": "decode_spec", **base, "draft_k": k,
            "draft_dim": max(dim // 4, 16), "draft_layers": 2,
            "new_tokens_per_sec": round(batch * steps / dt, 1)}),
            flush=True)
        # sampled speculative (rejection scheme, r5): distribution-
        # preserving, so this row is comparable to decode_sample
        spec_r = jax.jit(lambda p, dp, toks, r: T.speculative_sample(
            p, cfg, dp, dcfg, toks, steps=steps, rng=r, draft_k=k,
            temperature=0.8, top_p=0.95))
        dt = timed("spec_sample", spec_r, params, dparams, prompt,
                   jax.random.key(11))
        print(json.dumps({
            "bench": "decode_spec_sample", **base, "draft_k": k,
            "temperature": 0.8, "top_p": 0.95,
            "new_tokens_per_sec": round(batch * steps / dt, 1)}),
            flush=True)

    if "gqa" in modes:
        # same model size, KV heads / 4: the cache (and its per-step
        # HBM read, the decode bottleneck) shrinks 4x — this row
        # measures how much of that shows up as throughput
        kv = max(1, n_heads // 4)
        gcfg = T.TransformerConfig(vocab=vocab, dim=dim,
                                   n_layers=n_layers, n_heads=n_heads,
                                   n_kv_heads=kv, attn_impl="dense")
        gparams = T.init_params(jax.random.key(0), gcfg)
        gen_g = jax.jit(lambda p, toks: T.generate(p, gcfg, toks,
                                                   steps=steps))
        dt = timed(f"gqa_kv{kv}", gen_g, gparams, prompt)
        print(json.dumps({
            "bench": "decode_gqa", **base, "n_kv_heads": kv,
            "new_tokens_per_sec": round(batch * steps / dt, 1)}),
            flush=True)


def bench_engine(*, slots: int = 8, n_requests: int = 32,
                 prompt_bucket: int = 128, steps: int = 128,
                 dim: int = 512, n_layers: int = 8, n_heads: int = 8,
                 vocab: int = 32000):
    """Continuous-batching serving throughput (serve.engine): mixed
    prompt lengths padded to ONE bucket, n_requests streamed through
    `slots` decode slots, vs the LOCKSTEP baseline (generate() on
    ceil(N/S) fixed batches — the reference's SequenceGenerator
    service model) on the identical workload. The engine's win is
    utilization: lockstep batches idle finished rows until the whole
    batch drains; with eos-staggered finishes the gap widens (here
    all requests run full `steps`, so this measures the engine's
    per-slot-position OVERHEAD — the honest floor, not the best case).
    """
    from paddle_tpu.models import transformer as T
    from paddle_tpu.serve.engine import DecodeEngine

    cfg = T.TransformerConfig(vocab=vocab, dim=dim, n_layers=n_layers,
                              n_heads=n_heads, attn_impl="dense")
    params = T.init_params(jax.random.key(0), cfg)
    r = np.random.RandomState(0)
    prompts = [r.randint(0, vocab, (prompt_bucket,)).astype(np.int32)
               for _ in range(n_requests)]
    max_len = prompt_bucket + steps

    eng = DecodeEngine(params, cfg, slots=slots, max_len=max_len)
    progress(f"engine: warmup (S={slots} N={n_requests} "
             f"T0={prompt_bucket} steps={steps})")
    eng.serve(prompts[:slots], max_new=4)  # compile prefill+step
    progress("engine: timing serve()")
    t0 = time.perf_counter()
    out = eng.serve(prompts, max_new=steps)
    dt = time.perf_counter() - t0
    total = sum(len(o) for o in out)
    print(json.dumps({
        "bench": "serve_engine", "slots": slots,
        "n_requests": n_requests, "prompt_len": prompt_bucket,
        "steps": steps, "new_tokens_per_sec": round(total / dt, 1)}),
        flush=True)

    # lockstep baseline: same requests in fixed batches of `slots`
    gen = jax.jit(lambda p, toks: T.generate(p, cfg, toks, steps=steps))
    batch0 = jnp.asarray(np.stack(prompts[:slots]))
    jax.block_until_ready(gen(params, batch0))  # compile
    progress("engine: timing lockstep baseline")
    t0 = time.perf_counter()
    outs = []
    for i in range(0, n_requests, slots):
        chunk = prompts[i:i + slots]
        while len(chunk) < slots:       # ragged tail padded (lockstep
            chunk = chunk + [chunk[-1]]  # must run the full batch)
        outs.append(gen(params, jnp.asarray(np.stack(chunk))))
    jax.block_until_ready(outs)
    dt = time.perf_counter() - t0
    print(json.dumps({
        "bench": "serve_lockstep", "slots": slots,
        "n_requests": n_requests, "prompt_len": prompt_bucket,
        "steps": steps,
        "new_tokens_per_sec": round(n_requests * steps / dt, 1)}),
        flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="small shapes/iters (CPU smoke test)")
    ap.add_argument("--only", default=None,
                    help="comma-separated bench names")
    ap.add_argument("--batches", default=None,
                    help="comma-separated batch sizes to keep for the image "
                         "benches (the campaign uses this to defer the "
                         "biggest compiles to its wedge-risk tail)")
    args = ap.parse_args()

    from paddle_tpu.core import dtypes

    dtypes.set_default_policy(dtypes.bf16_compute_policy())
    on_tpu = _init_devices_or_die()[0].platform != "cpu"
    quick = args.quick or not on_tpu
    hw = 128 if quick else 224  # stride stacks collapse below ~96px
    iters = 2 if quick else 20

    image_cfgs = [(n, b) for n in ("alexnet", "googlenet", "vgg19",
                                   "resnet50", "resnet50_s2d",
                                   "resnet50_remat", "resnet50_remat_full")
                  for b in ((64,) if quick else (64, 128, 256))]
    # the reference's AlexNet table has a bs-512 row (benchmark/README.md)
    if not quick:
        image_cfgs.append(("alexnet", 512))
    # SmallNet runs at its native 32x32 (the reference table's config)
    image_cfgs += [("smallnet", b)
                   for b in ((64,) if quick else (64, 128, 256, 512))]
    if args.batches:
        keep = {int(b) for b in args.batches.split(",")}
        image_cfgs = [(n, b) for n, b in image_cfgs if b in keep]
    lstm_cfgs = [("lstm_h256", 256, 64), ("lstm_h512", 512, 64)]
    if not quick:  # the big/extra rows of the published table
        lstm_cfgs += [("lstm_h1280", 1280, 64),
                      ("lstm_h256", 256, 128), ("lstm_h512", 512, 128)]
    only = set(args.only.split(",")) if args.only else None

    for name, batch in image_cfgs:
        if only and name not in only:
            continue
        dt = bench_image(name, batch, hw=32 if name == "smallnet" else hw,
                         iters=iters)
        rec = {
            "bench": name, "batch": batch,
            "ms_per_batch": round(1000 * dt, 2),
            "imgs_per_sec": round(batch / dt, 1),
        }
        ref = REF.get((name, batch))
        if ref and not quick:
            rec["ref_ms_per_batch"] = round(ref, 1)
            rec["speedup_vs_ref"] = round(ref / (1000 * dt), 2)
        if not quick and name in FWD_GFLOPS:
            tflops = (batch / dt) * 3 * FWD_GFLOPS[name] / 1000
            rec["mfu_pct"] = round(100 * tflops / V5E_PEAK_TFLOPS, 1)
        print(json.dumps(rec))

    if not only or "seq2seq" in only:
        rec = bench_seq2seq(
            batch=16 if quick else 64,
            src_len=8 if quick else 30, tgt_len=8 if quick else 30,
            hidden=32 if quick else 512, embed=16 if quick else 256,
            vocab=500 if quick else 30000, iters=iters)
        print(json.dumps(rec))

    if not only or "ctr" in only:
        rec = bench_ctr_sparse(
            batch=256 if quick else 4096, slots=8 if quick else 32,
            vocab=10_000 if quick else 1_000_000,
            dim=16 if quick else 64, iters=iters)
        print(json.dumps(rec))

    if not only or "transformer" in only:
        rec = bench_transformer_lm(
            seq_len=128 if quick else 8192, batch=2 if quick else 4,
            dim=64 if quick else 512, n_layers=2 if quick else 8,
            n_heads=2 if quick else 8, vocab=500 if quick else 32000,
            iters=iters)
        print(json.dumps(rec))
        # sliding-window variant at the same shape: measures the flash
        # kernel's out-of-band block skipping (fwd O(T*window))
        rec = bench_transformer_lm(
            seq_len=128 if quick else 8192, batch=2 if quick else 4,
            dim=64 if quick else 512, n_layers=2 if quick else 8,
            n_heads=2 if quick else 8, vocab=500 if quick else 32000,
            iters=iters, window=32 if quick else 1024)
        print(json.dumps(rec))

    if only and ("decode" in only or "decode_greedy" in only):  # opt-in
        # decode_greedy: the cheap mode alone (bench.py's driver line);
        # decode: bench_decode's full default mode list (campaign's
        # suite_decode stage) — ONE authoritative list, in the function
        bench_decode(  # prints one record per mode itself
            batch=2 if quick else 8, prompt_len=16 if quick else 128,
            steps=8 if quick else 128, dim=64 if quick else 512,
            n_layers=2 if quick else 8, n_heads=2 if quick else 8,
            vocab=500 if quick else 32000, iters=2 if quick else 5,
            **({"modes": ("greedy",)} if "decode" not in only else {}))

    if only and "seq2seq_fused_ce" in only:  # opt-in A/B row (r5)
        # same shape as the north-star seq2seq row; the delta is the
        # chunked fused CE over the 30k-vocab decoder head (exact
        # parity; measured-before-default rule)
        rec = bench_seq2seq(
            batch=16 if quick else 64,
            src_len=8 if quick else 30, tgt_len=8 if quick else 30,
            hidden=32 if quick else 512, embed=16 if quick else 256,
            vocab=500 if quick else 30000, iters=iters,
            fused_ce_chunk=64 if quick else 512)
        print(json.dumps(rec))

    if only and "transformer_fused_ce" in only:  # opt-in A/B row
        # same shape as the default transformer row; the delta is the
        # chunked fused cross-entropy (losses.chunked_lm_head_nll)
        # dropping the 4.19 GiB f32 logits round-trip (-81% residual
        # set, tests/test_compiled_cost.py::TestFusedCEResiduals)
        rec = bench_transformer_lm(
            seq_len=128 if quick else 8192, batch=2 if quick else 4,
            dim=64 if quick else 512, n_layers=2 if quick else 8,
            n_heads=2 if quick else 8, vocab=500 if quick else 32000,
            iters=iters, fused_ce_chunk=512 if quick else 2048)
        print(json.dumps(rec))

    if only and "engine" in only:  # opt-in serving row (r5)
        bench_engine(
            slots=2 if quick else 8, n_requests=4 if quick else 32,
            prompt_bucket=8 if quick else 128, steps=8 if quick else 128,
            dim=64 if quick else 512, n_layers=2 if quick else 8,
            n_heads=2 if quick else 8, vocab=500 if quick else 32000)

    if only and "moe" in only:  # opt-in (not in the default campaign)
        rec = bench_moe_lm(
            seq_len=128 if quick else 2048, batch=2 if quick else 8,
            dim=64 if quick else 512, n_layers=2 if quick else 8,
            n_heads=2 if quick else 8, vocab=500 if quick else 32000,
            experts=4 if quick else 8, iters=iters)
        print(json.dumps(rec))

    if not only or "trainer_loop" in only:
        raw = bench_image("resnet50", 64 if quick else 256, hw=hw,
                          iters=iters)
        loop = bench_trainer_loop("resnet50", 64 if quick else 256, hw=hw,
                                  iters=iters)
        print(json.dumps({
            "bench": "trainer_loop_resnet50",
            "ms_per_batch": round(1000 * loop, 2),
            "raw_step_ms_per_batch": round(1000 * raw, 2),
            "loop_overhead_pct": round(100 * (loop - raw) / raw, 1),
        }))

    for name, hidden, batch in lstm_cfgs:
        if only and name not in only:
            continue
        dt = bench_lstm(hidden, batch, seq_len=16 if quick else 100,
                        vocab=1000 if quick else 10000, iters=iters)
        rec = {
            "bench": name, "batch": batch,
            "ms_per_batch": round(1000 * dt, 2),
            "seqs_per_sec": round(batch / dt, 1),
        }
        ref = REF.get((name, batch))
        if ref and not quick:
            rec["ref_ms_per_batch"] = round(ref, 1)
            rec["speedup_vs_ref"] = round(ref / (1000 * dt), 2)
        print(json.dumps(rec))


if __name__ == "__main__":
    main()
