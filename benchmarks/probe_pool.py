"""A/B the two maxpool backward formulations on the real chip.

Round-3 question: suite resnet50 bs64 measured 40.4 ms/batch on
2026-07-31 vs 31.3 ms in round 1. The tie-split maxpool VJP (committed
f098b23, after the last good measurement window) is the prime suspect;
relay-condition drift is the alternative. This probe times the SAME
ResNet-50 bs-64 train step (bench.bench_resnet — the one implementation
of the headline timing protocol) under both gradients — each in its own
subprocess because PADDLE_TPU_POOL_TIE_SPLIT is read at trace time, so
one jit compile freezes the choice per process — and prints the two
numbers side by side.

Run: python benchmarks/probe_pool.py
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CHILD = "--child"


def child() -> None:
    import jax

    # the TPU plugin force-selects its platform at config level,
    # outranking JAX_PLATFORMS — mirror a cpu request into the config so
    # a cpu smoke run never claims (or hangs on) the chip
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        jax.config.update("jax_platforms", "cpu")

    import bench

    tie = os.environ.get("PADDLE_TPU_POOL_TIE_SPLIT", "0") != "0"
    on_tpu = bench.init_devices_or_die()[0].platform != "cpu"
    batch, iters = (64, 30) if on_tpu else (8, 3)

    def emit(batch_, ms, imgs_per_sec):
        print(json.dumps({"probe": "pool_ab", "tie_split": tie,
                          "batch": batch_,
                          "ms_per_batch": round(ms, 2),
                          "imgs_per_sec": round(imgs_per_sec, 1)}),
              flush=True)

    bench.bench_resnet(batch_override=batch, iters_override=iters,
                       emit_fn=emit)


def main() -> None:
    # bench.run_child supplies the one shared child-reaping policy
    # (SIGTERM + 60s grace before SIGKILL — a hard-killed relay claimant
    # can wedge the chip); per-arm timeout keeps a wedging compile in
    # one arm from starving the other.
    from bench import run_child

    here = os.path.abspath(__file__)
    failures = 0
    for tie in (False, True):
        os.environ["PADDLE_TPU_POOL_TIE_SPLIT"] = "1" if tie else "0"
        print(f"[probe_pool] tie_split={tie} ...", file=sys.stderr, flush=True)
        rc, lines = run_child(f"probe_pool tie_split={tie}",
                              [sys.executable, here, CHILD], 600)
        got = False
        for line in lines:
            if line.strip().startswith("{"):
                print(line.strip(), flush=True)
                got = True
        if rc != 0 or not got:
            failures += 1
            print(f"[probe_pool] FAILED arm tie_split={tie} "
                  f"(rc={rc}, json={got}) — A/B incomplete",
                  file=sys.stderr, flush=True)
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == CHILD:
        child()
    else:
        main()
