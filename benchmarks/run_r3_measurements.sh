#!/bin/bash
# Round-3 chip measurement campaign, wedge-aware revision (r3b).
#
# What happened to r3a (2026-07-31 01:01-01:21): bench.py produced the
# seq2seq + CTR north stars, then the relay's remote-compile endpoint
# dropped the ResNet bs-256 compile ("response body closed"); the suite
# retry hung 13 min in the same compile and killing it wedged the chip
# (tiny-matmul probe now times out). Lessons encoded here:
#   * cheap compiles first — every stage that compiles at bs<=128 runs
#     before anything that compiles at bs256;
#   * the pool A/B probe runs early (it answers this round's open
#     regression question at bs64);
#   * bench.py is now internally subprocess-isolated with retry+fallback
#     so it can never lose already-printed metrics to a late crash;
#   * big-batch image rows run LAST, each in its own stage, so a
#     wedging compile costs only the stages after it.
#
# Each stage is subprocess-isolated with a timeout and logs to
# benchmarks/r3_logs/.
set -u
cd "$(dirname "$0")/.."
mkdir -p benchmarks/r3_logs

run() {  # name timeout cmd...
  local name=$1 tmo=$2; shift 2
  echo "=== $name ($(date +%H:%M:%S)) ==="
  timeout "$tmo" "$@" > "benchmarks/r3_logs/$name.out" 2> "benchmarks/r3_logs/$name.err"
  local rc=$?
  echo "    rc=$rc  (tail of out:)"; tail -3 "benchmarks/r3_logs/$name.out" | sed 's/^/    /'
}

# 0. liveness
run probe 180 python -c "import jax, jax.numpy as jnp; print((jnp.ones((128,128),jnp.bfloat16)@jnp.ones((128,128),jnp.bfloat16))[0,0])"

# 1. the open regression question: tie-split vs select-and-scatter
#    maxpool backward, resnet bs64 (cheap compile, done twice)
run probe_pool 1500 python benchmarks/probe_pool.py

# 2. lstm benches (fused kernel) + the h256/h512 inversion probe
run suite_lstm 1200 python benchmarks/suite.py --only lstm_h256,lstm_h512
run probe_lstm 1200 python benchmarks/probe_lstm.py

# 3. CTR stage probe (steady-state attribution after the recompile fix)
run probe_ctr 1200 python benchmarks/probe_ctr.py

# 4. cheap suite rows: smallnet, trainer-loop overhead, transformer
#    (all compile small; seq2seq/ctr are NOT here — the bench stage
#    below runs them via bench.py, no duplicate chip time)
run suite_small 2400 python benchmarks/suite.py --only smallnet,trainer_loop
run suite_misc 2400 python benchmarks/suite.py --only transformer

# 5. the north stars, driver-format (resnet bs256 inside, isolated+retry;
#    worst case 2x(1200+60)s suite stages + 3x(900+60)s resnet attempts
#    = 5400s, plus margin for interpreter startup — a stage timeout that
#    SIGTERMs bench.py mid-reap would orphan a grandchild holding the
#    relay claim, the exact wedge this script exists to avoid)
run bench 5700 python bench.py

# 6. image suite, batch-ascending; big-batch rows are the wedge risk so
#    they go last, one stage each
run suite_alexnet 1800 python benchmarks/suite.py --only alexnet --batches 64,128,256
run suite_googlenet 1800 python benchmarks/suite.py --only googlenet
run suite_resnet 1800 python benchmarks/suite.py --only resnet50
run suite_resnet_s2d 1800 python benchmarks/suite.py --only resnet50_s2d
run suite_vgg 1800 python benchmarks/suite.py --only vgg19

# 6b. MoE transformer row (opt-in bench; T=2048 compiles small)
run suite_moe 1800 python benchmarks/suite.py --only moe

# 6c. KV-cache decode throughput (serving latency analog)
run suite_decode 1800 python benchmarks/suite.py --only decode

# 7. refreshed profile trace for PROFILE_NOTES
run profile 1200 python benchmarks/profile_step.py --batch 256 --iters 10

# 8. the single biggest compile (alexnet bs512, the reference table's
#    last row) dead last: if it wedges the chip nothing is behind it
run suite_alexnet512 1800 python benchmarks/suite.py --only alexnet --batches 512

echo "=== done ($(date +%H:%M:%S)) — logs in benchmarks/r3_logs/ ==="
