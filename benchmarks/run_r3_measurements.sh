#!/bin/bash
# Round-3 chip measurement campaign — run the moment the TPU answers.
# Each stage is subprocess-isolated with a timeout (a pathological
# compile must not take the whole campaign down) and logs to
# benchmarks/r3_logs/. Order: cheap probes first, the big suite last,
# so partial chip time still yields the highest-value numbers.
set -u
cd "$(dirname "$0")/.."
mkdir -p benchmarks/r3_logs

run() {  # name timeout cmd...
  local name=$1 tmo=$2; shift 2
  echo "=== $name ($(date +%H:%M:%S)) ==="
  timeout "$tmo" "$@" > "benchmarks/r3_logs/$name.out" 2> "benchmarks/r3_logs/$name.err"
  local rc=$?
  echo "    rc=$rc  (tail of out:)"; tail -3 "benchmarks/r3_logs/$name.out" | sed 's/^/    /'
}

# 0. liveness
run probe 180 python -c "import jax, jax.numpy as jnp; print((jnp.ones((128,128),jnp.bfloat16)@jnp.ones((128,128),jnp.bfloat16))[0,0])"

# 1. the north stars, driver-format (fixed CTR, fused-GRU seq2seq)
run bench 2400 python bench.py

# 2. resnet50 plain vs s2d stem (the profile-driven fix)
run suite_resnet 1800 python benchmarks/suite.py --only resnet50,resnet50_s2d

# 3. lstm benches (now on the fused kernel) + inversion probe
run suite_lstm 1200 python benchmarks/suite.py --only lstm_h256,lstm_h512
run probe_lstm 1200 python benchmarks/probe_lstm.py

# 4. CTR stage probe (steady-state attribution after the recompile fix)
run probe_ctr 1200 python benchmarks/probe_ctr.py

# 5. the rest of the published-config suite
run suite_images 3600 python benchmarks/suite.py --only alexnet,googlenet,vgg19,smallnet
run suite_misc 2400 python benchmarks/suite.py --only seq2seq,ctr,transformer,trainer_loop

# 6. refreshed profile trace for PROFILE_NOTES
run profile 1200 python benchmarks/profile_step.py --batch 256 --iters 10

echo "=== done ($(date +%H:%M:%S)) — logs in benchmarks/r3_logs/ ==="
